"""Static extractor of the platform's cross-process HTTP wire surface.

The platform stopped being one process around PR 15: the rig runs N
gateway replicas, per-shard store processes, dispatcher pools, and
drain-aware workers as separate OS processes talking HTTP — and the
contracts between them (which routes exist, which headers round-trip,
which refusal statuses a caller must distinguish) are exactly the things
no per-process test can see drifting. This module extracts that surface
once per analyzer run, shared by the three wire rules (AIL016–AIL018 in
``rules/wire.py``) and the ``--dump-wire`` table generator:

- **server routes** — every ``router.add_get/add_post/add_put/
  add_delete/add_route`` registration, with the path resolved through
  module-level string constants (``DRAIN_PATH``), cross-module imports
  of those constants, and prefix concatenations
  (``self.service.prefix + "/models/{name}/reload"`` becomes the
  leading multi-segment wildcard ``{**}``);
- **client call sites** — literal path references reaching the wire:
  aiohttp session verbs (``session.post(base + FEED_PATH)``),
  ``urllib.request.urlopen``/``Request``, the store-client idioms
  (``self._request("GET", "/v1/taskstore/task")``,
  ``self._routed(tid, "POST", path)``), and the rig's blocking helpers
  (``_http_json``/``_fetch_text``). One level of local-variable
  resolution (``url = base + X; session.post(url)``) is followed;
- **header uses** — every literal (or constant-resolved) occurrence of
  an ``X-*`` / ``Retry-After`` header name, classified by syntactic
  context into *emit* (dict-literal key, ``headers[...] = v``,
  ``setdefault``/``add``), *read* (``.get/.getone/.pop``, subscript
  load, ``in`` membership), or *mention* (strip lists, constant
  definitions);
- **refusal statuses** — per registered route, the distinguished
  refusal statuses (409/429/503/504) its resolved handler demonstrably
  mints (literal ``status=`` on ``Response``/``json_response``, the
  ``web.HTTPConflict``-family constructors), followed one call hop into
  same-module helpers; and per client call site, the statuses its
  enclosing function visibly branches on, plus whether it propagates
  the raw response to ITS caller.

Path shapes are segment tuples where ``{*}`` matches exactly one
segment and ``{**}`` matches any run of segments — ``{param}`` route
placeholders become ``{*}``, ``{tail:.*}`` and unresolvable prefixes
become ``{**}``. A registration or call whose path has no literal
segment at all is *dynamic* (config-driven, e.g. the gateway's
published routes.json surface) and is deliberately excluded from drift
matching — config wiring is checked by the deployment tests, not by
this pass.

Stdlib-only, like everything under ``analysis/``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .core import ModuleContext, ProjectContext, import_aliases

#: One-segment / multi-segment wildcards in canonical path shapes.
SEG_ONE = "{*}"
SEG_MANY = "{**}"

#: Session-verb attribute names that take the URL as the first argument.
_VERB_ATTRS = {"get": "GET", "post": "POST", "put": "PUT",
               "delete": "DELETE", "patch": "PATCH", "head": "HEAD"}
#: Route-registration attribute names.
_REG_ATTRS = {"add_get": "GET", "add_post": "POST", "add_put": "PUT",
              "add_delete": "DELETE", "add_patch": "PATCH",
              "add_head": "HEAD"}
#: ``aiohttp.web`` refusal constructors and their statuses (only the
#: distinguished ones AIL018 cares about).
_HTTP_EXC_STATUS = {"HTTPConflict": 409, "HTTPTooManyRequests": 429,
                    "HTTPServiceUnavailable": 503,
                    "HTTPGatewayTimeout": 504}
#: Statuses a caller must visibly distinguish from generic failure.
DISTINGUISHED_STATUSES = frozenset({409, 429, 503, 504})

#: Header-name domain of the wire vocabulary: the platform's extension
#: headers plus the one standard header the refusal contract is built on.
_HEADER_RE = re.compile(r"^X-[A-Za-z0-9][A-Za-z0-9-]*$")
_NAMED_HEADERS = frozenset({"Retry-After"})

_GETTERS = {"get", "getone", "getall", "pop"}
_SETTERS = {"setdefault", "add"}

_DYN = "\x00"  # placeholder for a dynamic fragment inside a joined path


def is_wire_header(name: str) -> bool:
    return bool(_HEADER_RE.match(name)) or name in _NAMED_HEADERS


@dataclass(frozen=True)
class RouteReg:
    method: str                    # "GET"… or "*" (any)
    shape: tuple[str, ...]
    display: str                   # canonical "/v1/…/{*}" form
    path: str                      # registering module (repo-relative)
    line: int
    handler: str = ""              # resolved handler symbol name
    dynamic: bool = False          # no literal segment — excluded from drift
    statuses: frozenset[int] = frozenset()

    @property
    def key(self) -> tuple[str, tuple[str, ...]]:
        return (self.method, self.shape)


@dataclass(frozen=True)
class ClientRef:
    method: str                    # "GET"… or "*" (unresolvable)
    shape: tuple[str, ...]
    display: str
    path: str
    line: int
    symbol: str = ""               # enclosing function qualname
    handled: frozenset[int] = frozenset()  # statuses the function branches on
    propagates: bool = False       # returns the raw response to its caller


@dataclass(frozen=True)
class HeaderUse:
    name: str
    kind: str                      # "emit" | "read" | "mention"
    path: str
    line: int


@dataclass
class WireSurface:
    routes: list[RouteReg] = field(default_factory=list)
    clients: list[ClientRef] = field(default_factory=list)
    headers: list[HeaderUse] = field(default_factory=list)

    # -- matching ----------------------------------------------------------

    def matchable_routes(self) -> list[RouteReg]:
        """Routes drift can be checked against: at least one literal
        segment, and not a catch-all proxy (a shape that accepts every
        path can neither evidence nor refute a client's)."""
        return [r for r in self.routes
                if not r.dynamic and any(
                    s not in (SEG_ONE, SEG_MANY) for s in r.shape)]

    def routes_for(self, ref: ClientRef) -> list[RouteReg]:
        return [r for r in self.matchable_routes()
                if _method_ok(ref.method, r.method)
                and shapes_match(r.shape, ref.shape)]

    def clients_for(self, route: RouteReg) -> list[ClientRef]:
        return [c for c in self.clients
                if _method_ok(c.method, route.method)
                and shapes_match(route.shape, c.shape)]


def _method_ok(client_method: str, route_method: str) -> bool:
    return (client_method == "*" or route_method == "*"
            or client_method == route_method)


def shapes_match(server: tuple[str, ...], client: tuple[str, ...]) -> bool:
    """Segment-wise match where either side's ``{*}`` matches one segment
    and ``{**}`` matches any run (possibly empty) of segments."""

    def rec(a: tuple[str, ...], b: tuple[str, ...]) -> bool:
        if not a:
            return not b or all(s == SEG_MANY for s in b)
        if not b:
            return all(s == SEG_MANY for s in a)
        x, y = a[0], b[0]
        if x == SEG_MANY:
            return rec(a[1:], b) or rec(a, b[1:])
        if y == SEG_MANY:
            return rec(a, b[1:]) or rec(a[1:], b)
        if x == SEG_ONE or y == SEG_ONE or x == y:
            return rec(a[1:], b[1:])
        return False

    return rec(server, client)


def parse_shape(display: str) -> tuple[str, ...]:
    """Canonical-display (or doc-table) path → shape tuple. ``{tail:.*}``
    and ``{**}``/``{prefix}`` are multi-wildcards; any other ``{…}``
    placeholder is one segment."""
    display = display.split("?", 1)[0]
    segs: list[str] = []
    for raw in display.strip("/").split("/"):
        if not raw:
            continue
        if raw in (SEG_MANY, "{prefix}") or (
                raw.startswith("{") and ":" in raw and raw.endswith("}")):
            segs.append(SEG_MANY)
        elif "{" in raw or "<" in raw:
            segs.append(SEG_ONE)
        else:
            segs.append(raw)
    return tuple(segs)


def shape_display(shape: tuple[str, ...]) -> str:
    return "/" + "/".join(shape) if shape else "/"


# -- expression → path parts -------------------------------------------------


class _ConstMap:
    """Project-wide module-level string constants, for resolving
    ``DRAIN_PATH``-style names at registration/call/header sites. Keyed
    by bare name; a name bound to DIFFERENT values in different modules
    is ambiguous and resolves to nothing (conservative)."""

    _AMBIGUOUS = object()

    def __init__(self, modules: list[ModuleContext]):
        self._by_name: dict[str, object] = {}
        for m in modules:
            for node in m.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                if not (isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    continue
                name, value = node.targets[0].id, node.value.value
                prior = self._by_name.get(name)
                if prior is None:
                    self._by_name[name] = value
                elif prior != value:
                    self._by_name[name] = self._AMBIGUOUS

    def lookup(self, name: str) -> str | None:
        value = self._by_name.get(name)
        return value if isinstance(value, str) else None


def _name_of(expr: ast.AST) -> str | None:
    """Bare name of a Name or the attr of an Attribute (``FEED_PATH`` and
    ``wire.FEED_PATH`` both resolve through the constant map)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _path_parts(expr: ast.AST, consts: _ConstMap,
                local: dict[str, ast.AST] | None = None,
                depth: int = 0) -> list[str]:
    """Flatten a URL expression into literal fragments and ``_DYN``
    markers, resolving constants and (one level of) local assignments."""
    if depth > 6:
        return [_DYN]
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return (_path_parts(expr.left, consts, local, depth + 1)
                + _path_parts(expr.right, consts, local, depth + 1))
    if isinstance(expr, ast.JoinedStr):
        out: list[str] = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                # f"http://{host}:{port}{DRAIN_PATH}" — a braced constant
                # name still resolves; everything else is dynamic.
                out.extend(_path_parts(v.value, consts, local, depth + 1))
            else:
                out.append(_DYN)
        return out
    name = _name_of(expr)
    if name is not None:
        if local and name in local and isinstance(expr, ast.Name):
            target = local[name]
            if target is not expr:
                return _path_parts(target, consts, None, depth + 1)
        value = consts.lookup(name)
        if value is not None:
            return [value]
    if (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr in ("rstrip", "strip", "format")):
        # ``base.rstrip("/") + path`` — the receiver carries the text.
        return _path_parts(expr.func.value, consts, local, depth + 1)
    return [_DYN]


def shape_from_parts(parts: list[str]) -> tuple[str, ...] | None:
    """Join fragments, locate the path, normalize to a shape. Returns
    None when no literal path fragment is present (fully dynamic)."""
    joined = "".join(parts)
    if "/" not in joined.replace("://", ""):
        return None
    # Drop a scheme+host prefix: the path starts at the first "/" after
    # the authority (or at a leading "/" when there is no scheme).
    if "://" in joined:
        after = joined.split("://", 1)[1]
        idx = after.find("/")
        if idx < 0:
            return None
        joined = after[idx:]
    else:
        idx = joined.find("/")
        # A dynamic prefix before the first literal "/" is a base URL.
        joined = joined[idx:]
    joined = joined.split("?", 1)[0]
    segs: list[str] = []
    for raw in joined.strip("/").split("/"):
        if not raw:
            continue
        if raw == _DYN * len(raw) and raw:
            segs.append(SEG_ONE)
        elif raw.startswith("{") and ":" in raw and raw.endswith("}"):
            segs.append(SEG_MANY)
        elif "{" in raw or _DYN in raw:
            segs.append(SEG_ONE)
        else:
            segs.append(raw)
    if not segs or all(s in (SEG_ONE, SEG_MANY) for s in segs):
        return None
    # A leading dynamic fragment glued to the path ("{base}/v1/x" keeps
    # its "/" — already handled), but a *prefix expression* like
    # ``self.prefix + "/models"`` arrives as [DYN, "/models"]: the DYN
    # consumed above was before the first "/", so nothing to do here.
    return tuple(segs)


def _leading_dynamic(parts: list[str]) -> bool:
    """True when the joined expression starts with a dynamic fragment
    that is NOT a full base URL — i.e. a route prefix (``self.prefix +
    "/models"``), which must match as a leading multi-wildcard."""
    for p in parts:
        if p == _DYN:
            return True
        if p.strip():
            return False
    return False


# -- module walking ----------------------------------------------------------


class _ParentVisitor(ast.NodeVisitor):
    """One walk that records parents + enclosing function per node."""

    def __init__(self):
        self.parents: dict[ast.AST, ast.AST] = {}
        self.funcs: dict[ast.AST, ast.AST | None] = {}
        self._fn_stack: list[ast.AST] = []
        self._name_stack: list[str] = []

    def generic_visit(self, node):
        is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        is_scope = is_fn or isinstance(node, ast.ClassDef)
        if is_fn:
            self._fn_stack.append(node)
        if is_scope:
            self._name_stack.append(node.name)
        for child in ast.iter_child_nodes(node):
            self.parents[child] = node
            self.funcs[child] = self._fn_stack[-1] if self._fn_stack else None
            self.generic_visit(child)
        if is_fn:
            self._fn_stack.pop()
        if is_scope:
            self._name_stack.pop()


def _qualname(visitor: _ParentVisitor, node: ast.AST) -> str:
    names = []
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = visitor.parents.get(cur)
    return ".".join(reversed(names))


def _local_assigns(fn: ast.AST | None) -> dict[str, ast.AST]:
    """name → assigned value for simple single-target assignments inside
    ``fn`` — names assigned more than once resolve to nothing."""
    if fn is None:
        return {}
    seen: dict[str, ast.AST | None] = {}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            name = node.targets[0].id
            seen[name] = None if name in seen else node.value
    return {k: v for k, v in seen.items() if v is not None}


# -- handler status extraction -----------------------------------------------


def _module_functions(tree: ast.Module) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _statuses_in(fn: ast.AST) -> set[int]:
    """Distinguished refusal statuses a function body visibly mints."""
    out: set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        fname = _name_of(node.func) or ""
        if fname in ("Response", "json_response", "StreamResponse"):
            for kw in node.keywords:
                if (kw.arg == "status" and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, int)):
                    out.add(kw.value.value)
        elif fname in _HTTP_EXC_STATUS:
            out.add(_HTTP_EXC_STATUS[fname])
    return out & set(DISTINGUISHED_STATUSES)


def _handler_statuses(handler_expr: ast.AST, tree: ast.Module) -> tuple[str, frozenset[int]]:
    """Resolve a registration's handler expression to a same-module
    function and collect its distinguished statuses, following ONE call
    hop into same-module helpers (``self._refuse(...)``); tuple-returning
    admission helpers and cross-module shells are beyond static reach and
    contribute nothing (under-approximation by design)."""
    expr = handler_expr
    # Unwrap single-argument wrappers: ``stamped(upsert)``.
    if isinstance(expr, ast.Call) and expr.args:
        inner = expr.args[0]
        if _name_of(inner) is not None:
            expr = inner
    name = _name_of(expr)
    if name is None:
        return "", frozenset()
    fns = _module_functions(tree)
    fn = fns.get(name)
    if fn is None:
        return name, frozenset()
    statuses = _statuses_in(fn)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = _name_of(node.func)
            if callee and callee != name and callee in fns:
                statuses |= _statuses_in(fns[callee])
    return name, frozenset(statuses)


# -- client-side status handling ---------------------------------------------


def _ints_in_compares(fn: ast.AST) -> frozenset[int]:
    """Every int literal participating in a comparison (or membership
    tuple/set/list) inside ``fn`` — the statuses the function's branch
    structure can distinguish."""
    out: set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        for side in [node.left, *node.comparators]:
            if isinstance(side, ast.Constant) and isinstance(side.value, int):
                out.add(side.value)
            elif isinstance(side, (ast.Tuple, ast.Set, ast.List)):
                for el in side.elts:
                    if (isinstance(el, ast.Constant)
                            and isinstance(el.value, int)):
                        out.add(el.value)
    return frozenset(out)


def _response_names(visitor: _ParentVisitor, call: ast.Call) -> set[str]:
    """Names the call's response lands in: ``resp = await …`` /
    ``resp, body = await …`` / ``async with … as resp``."""
    names: set[str] = set()
    cur: ast.AST = call
    parent = visitor.parents.get(cur)
    while isinstance(parent, (ast.Await, ast.withitem)) or (
            isinstance(parent, (ast.With, ast.AsyncWith))):
        if isinstance(parent, ast.withitem):
            if isinstance(parent.optional_vars, ast.Name):
                names.add(parent.optional_vars.id)
        cur, parent = parent, visitor.parents.get(parent)
    if isinstance(parent, ast.Assign):
        for target in parent.targets:
            if isinstance(target, ast.Name):
                names.add(target.id)
            elif isinstance(target, ast.Tuple) and target.elts:
                # ``resp, body = await …`` — only the FIRST element is
                # the response; returning the parsed body does not hand
                # the status to the caller.
                first = target.elts[0]
                if isinstance(first, ast.Name):
                    names.add(first.id)
    return names


def _handled_with_helpers(fn: ast.AST, resp_names: set[str],
                          fns: dict[str, ast.AST],
                          base: frozenset[int]) -> frozenset[int]:
    """``base`` (the enclosing function's own compares) plus ONE call hop
    into same-module helpers the response is passed to —
    ``_raise_refusal(resp)`` — symmetric with the server-side hop in
    ``_handler_statuses``. The hop needs the response NAME as an
    argument: ``resp.raise_for_status()`` is an attribute call on the
    response and distinguishes nothing."""
    if not resp_names:
        return base
    out = set(base)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = _name_of(node.func)
        if (callee and callee in fns and fns[callee] is not fn
                and any(isinstance(a, ast.Name) and a.id in resp_names
                        for a in node.args)):
            out |= _ints_in_compares(fns[callee])
    return frozenset(out)


def _propagates(fn: ast.AST, resp_names: set[str]) -> bool:
    """The function hands the raw response (or its status) back to its
    caller — callers do the distinguishing (``_request`` helpers)."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Return, ast.Yield)) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in resp_names:
                    return True
                if isinstance(sub, ast.Attribute) and sub.attr == "status":
                    return True
        if isinstance(node, ast.Raise) and node.exc is not None:
            # ``raise StatusError(resp.status, …)`` — a *typed* carrier
            # the caller can branch on still counts as propagation only
            # when the response itself rides the exception; generic
            # message-formatting does not.
            continue
    return False


# -- the extractor -----------------------------------------------------------


def extract_wire_surface(ctx: ProjectContext,
                         extra_client_modules: list[ModuleContext] | None = None
                         ) -> WireSurface:
    """Build the project's wire surface. ``extra_client_modules`` lets
    the caller bring out-of-tree callers (``clients/python/``) in as
    client/header evidence without making them a registration surface."""
    surface = WireSurface()
    all_modules = list(ctx.modules) + list(extra_client_modules or [])
    consts = _ConstMap(all_modules)
    for module in ctx.modules:
        _extract_module(module, consts, surface, server=True)
    for module in extra_client_modules or []:
        _extract_module(module, consts, surface, server=False)
    return surface


def _extract_module(module: ModuleContext, consts: _ConstMap,
                    surface: WireSurface, server: bool) -> None:
    visitor = _ParentVisitor()
    visitor.parents[module.tree] = None  # type: ignore[assignment]
    visitor.generic_visit(module.tree)
    fn_handled: dict[ast.AST, frozenset[int]] = {}
    local_cache: dict[ast.AST, dict[str, ast.AST]] = {}
    module_fns = _module_functions(module.tree)

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            if server:
                _maybe_route(module, consts, surface, visitor, node)
            _maybe_client(module, consts, surface, visitor, node,
                          fn_handled, local_cache, module_fns)
    _extract_headers(module, consts, surface, visitor)


def _canonical_display(shape: tuple[str, ...]) -> str:
    return shape_display(shape)


def _maybe_route(module: ModuleContext, consts: _ConstMap,
                 surface: WireSurface, visitor: _ParentVisitor,
                 node: ast.Call) -> None:
    func = node.func
    if not isinstance(func, ast.Attribute):
        return
    method = _REG_ATTRS.get(func.attr)
    path_arg: ast.AST | None = None
    handler_arg: ast.AST | None = None
    if method is not None and node.args:
        path_arg = node.args[0]
        handler_arg = node.args[1] if len(node.args) > 1 else None
    elif func.attr == "add_route" and len(node.args) >= 2:
        m = node.args[0]
        method = (m.value.upper()
                  if isinstance(m, ast.Constant) and isinstance(m.value, str)
                  else "*")
        path_arg = node.args[1]
        handler_arg = node.args[2] if len(node.args) > 2 else None
    if method is None or path_arg is None:
        return
    # Only router registrations: the receiver chain must end in
    # ``.router`` or be ``app``-named (``self.app.router.add_get``,
    # ``app.router.add_post``) — keeps dict helpers named add_route
    # (e.g. the push webhook's topic map) off the surface.
    recv = func.value
    recv_name = _name_of(recv) or ""
    if recv_name != "router" and "router" not in recv_name:
        return
    fn = visitor.funcs.get(node)
    local = _local_assigns(fn)
    parts = _path_parts(path_arg, consts, local)
    leading_dyn = _leading_dynamic(parts)
    shape = shape_from_parts(parts)
    if shape is None:
        dynamic = True
        shape = (SEG_MANY,)
    else:
        dynamic = False
        if leading_dyn:
            shape = (SEG_MANY, *shape)
    handler = ""
    statuses: frozenset[int] = frozenset()
    if handler_arg is not None:
        handler, statuses = _handler_statuses(handler_arg, module.tree)
    surface.routes.append(RouteReg(
        method=method, shape=shape, display=_canonical_display(shape),
        path=module.path, line=node.lineno, handler=handler,
        dynamic=dynamic, statuses=statuses))


#: Bare or attribute calls that take the target URL as the first
#: argument: the stdlib entrypoints plus this codebase's blocking-helper
#:  idioms (rig drivers, rollout controller, observability pollers).
_URL_FIRST_FUNCS = frozenset({
    "urlopen", "_http_json", "_fetch_json", "_fetch_text",
    "fetch_json", "fetch_text", "http_json",
})


def _client_call_parts(node: ast.Call) -> tuple[str, ast.AST] | None:
    """(method, url_expr) when ``node`` is a recognized client call."""
    func = node.func
    fname = _name_of(func)
    if fname is None:
        return None
    if fname in _VERB_ATTRS:
        # ``session.get(url)`` — and the bare-name local wrappers the rig
        # drivers define (``get(base + "/v1/rig/ledgers")``). Bare names
        # are safe because every client ref is additionally gated on the
        # argument resolving to a literal path shape.
        if node.args:
            return _VERB_ATTRS[fname], node.args[0]
        return None
    if fname == "request" and isinstance(func, ast.Attribute):
        if len(node.args) >= 2:
            m = node.args[0]
            method = (m.value.upper() if isinstance(m, ast.Constant)
                      and isinstance(m.value, str) else "*")
            return method, node.args[1]
        return None
    if fname in _URL_FIRST_FUNCS:
        if node.args:
            method = "*"
            return method, node.args[0]
        return None
    if fname == "to_thread" and len(node.args) >= 2:
        # ``asyncio.to_thread(_http_json, url + PATH, body)`` — the url
        # is the wrapped callable's first argument. Accepted for any
        # callable: the shape gate keeps non-URL second arguments out.
        return "*", node.args[1]
    if fname == "Request":
        if node.args:
            method = "*"
            for kw in node.keywords:
                if (kw.arg == "method" and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    method = kw.value.value.upper()
            return method, node.args[0]
        return None
    if fname in ("_request", "_routed") and isinstance(func, ast.Attribute):
        offset = 0 if fname == "_request" else 1
        if len(node.args) >= offset + 2:
            m = node.args[offset]
            if isinstance(m, ast.Constant) and isinstance(m.value, str):
                return m.value.upper(), node.args[offset + 1]
        return None
    return None


def _maybe_client(module: ModuleContext, consts: _ConstMap,
                  surface: WireSurface, visitor: _ParentVisitor,
                  node: ast.Call,
                  fn_handled: dict[ast.AST, frozenset[int]],
                  local_cache: dict[ast.AST, dict[str, ast.AST]],
                  module_fns: dict[str, ast.AST]) -> None:
    got = _client_call_parts(node)
    if got is None:
        return
    method, url_expr = got
    fn = visitor.funcs.get(node)
    if fn is not None and fn not in local_cache:
        # Merge locals along the enclosing-function chain, outermost
        # first: a closure posting to ``url`` built one frame up (the
        # chaos driver's nested ``post()``) still resolves.
        chain: list[ast.AST] = []
        cur: ast.AST | None = fn
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                chain.append(cur)
            cur = visitor.parents.get(cur)
        merged: dict[str, ast.AST] = {}
        for outer in reversed(chain):
            merged.update(_local_assigns(outer))
        local_cache[fn] = merged
    parts = _path_parts(url_expr, consts,
                        local_cache.get(fn) if fn is not None else None)
    shape = shape_from_parts(parts)
    if shape is None:
        return  # fully dynamic — config-driven, not this pass's business
    handled: frozenset[int] = frozenset()
    propagates = False
    if fn is not None:
        if fn not in fn_handled:
            fn_handled[fn] = _ints_in_compares(fn)
        resp_names = _response_names(visitor, node)
        handled = _handled_with_helpers(fn, resp_names, module_fns,
                                        fn_handled[fn])
        propagates = _propagates(fn, resp_names)
    surface.clients.append(ClientRef(
        method=method, shape=shape, display=_canonical_display(shape),
        path=module.path, line=node.lineno,
        symbol=_qualname(visitor, node), handled=handled,
        propagates=propagates))


# -- headers -----------------------------------------------------------------


def _header_value(node: ast.AST, consts: _ConstMap) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if is_wire_header(node.value) else None
    name = _name_of(node)
    if name is not None and (name.endswith("_HEADER")
                             or name.endswith("_HDR")):
        value = consts.lookup(name)
        if value is not None and is_wire_header(value):
            return value
    return None


def _classify_header(visitor: _ParentVisitor, node: ast.AST) -> str:
    parent = visitor.parents.get(node)
    if isinstance(parent, ast.Dict) and node in parent.keys:
        return "emit"
    if isinstance(parent, ast.Subscript) and parent.slice is node:
        gp = visitor.parents.get(parent)
        if isinstance(gp, (ast.Assign, ast.AugAssign)) and (
                parent in getattr(gp, "targets", ()) or
                getattr(gp, "target", None) is parent):
            return "emit"
        if isinstance(gp, ast.Delete):
            return "emit"
        return "read"
    if isinstance(parent, ast.Call) and parent.args \
            and parent.args[0] is node \
            and isinstance(parent.func, ast.Attribute):
        if parent.func.attr in _GETTERS:
            return "read"
        if parent.func.attr in _SETTERS:
            return "emit"
    if isinstance(parent, ast.Compare):
        ops = parent.ops
        if any(isinstance(op, (ast.In, ast.NotIn)) for op in ops):
            return "read"
    return "mention"


def _extract_headers(module: ModuleContext, consts: _ConstMap,
                     surface: WireSurface,
                     visitor: _ParentVisitor) -> None:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            continue
        if isinstance(node, (ast.Constant, ast.Name, ast.Attribute)):
            value = _header_value(node, consts)
            if value is None:
                continue
            # The defining assignment itself is a mention, not an emit.
            surface.headers.append(HeaderUse(
                name=value, kind=_classify_header(visitor, node),
                path=module.path, line=getattr(node, "lineno", 1)))


# -- out-of-tree client evidence ---------------------------------------------


def load_extra_clients(root: str, parse) -> list[ModuleContext]:
    """Parse ``clients/python/*.py`` (the stdlib caller library) as extra
    client evidence. ``parse`` is ``core.parse_module`` (injected to ride
    the shared parse cache). Missing directory → no extra modules."""
    out: list[ModuleContext] = []
    base = os.path.join(root, "clients", "python")
    try:
        names = sorted(os.listdir(base))
    except OSError:
        return out
    for fname in names:
        if not fname.endswith(".py"):
            continue
        abspath = os.path.join(base, fname)
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        mod = parse(abspath, rel)
        if mod is not None:
            out.append(mod)
    return out
