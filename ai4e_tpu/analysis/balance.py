"""Paired-effect conservation engine (AIL020 — docs/analysis.md).

The platform's worst recurring bug class is *imbalance*: a counted effect
opened on one path and never closed on another — the PR 3 half-open
probe-slot leak, the PR 7 sync-proxy inflight pairing, the PR 8 device
failure raising out of ``batcher.submit`` past the buffered ledger flush,
the PR 18 drain straggler retirement. Each pair of verbs below is one of
those hand-found bugs turned into a declarative spec; the engine walks one
function at a time on top of ``AwaitFlow`` (the PR 5 CFG-over-suspension-
points) and asks: *does the close dominate every exit the open can reach —
return, raise, and the suspension-abandonment path?*

Scope is deliberately intra-function: an open whose close lives in a
DIFFERENT function (``_reserve`` in the handler prologue, ``_release`` in
the epilogue helper; ``begin_probe`` in admission, ``record_*`` in the
response path) is a protocol endpoint the engine cannot see both sides
of, so an open with no receiver-matched close anywhere in the same
function is skipped, not flagged. What remains — both sides present, one
frame — is exactly the shape every one of the past bugs had.

Blessed idioms (never flagged):

- the open is a context-manager entry (``with``/``async with`` item);
- the open sits in (or immediately before) a ``try`` whose ``finally``
  contains a matched close — the interpreter guarantees the close on
  return, raise, AND task cancellation;
- close-before-reraise: a matched close unconditionally preceding the
  ``raise`` inside the same handler covers that exit;
- ownership handoff: the open's result is stored into an attribute /
  container (or returned) — the effect now has a new owner with its own
  lifecycle (``seq.slot = slot; self._active[slot] = seq``);
- callback handoff: a matched close inside a nested ``def``/``lambda``
  (``task.add_done_callback(lambda _t: self._pending.dec())``) — the
  close rides the task, not this frame.

Everything else is an escape:

- ``return`` / ``raise`` not covered by a close on that path;
- falling off the end of the function (or of the open's enclosing loop
  iteration) without an unconditional close;
- **suspension abandonment**: an ``await`` between the open and its
  path-close, with no ``finally``/CM protection — a cancelled task
  abandons the frame at that await and the close never runs. This is the
  leak mode reviews miss: every path LOOKS closed until the event loop
  cancels you mid-flight.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from .core import AwaitFlow, _pos

__all__ = ["PairSpec", "PAIR_SPECS", "Escape", "check_all",
           "check_function"]


@dataclass(frozen=True)
class PairSpec:
    """One paired effect: ``opens`` must be balanced by ``closes``.

    ``receiver`` (regex) constrains which attribute chains count as opens
    — ``stamp`` is only a ledger-buffer open when called on something that
    looks like a ledger. ``same_receiver`` demands the close ride the
    exact same chain (gauges: ``x.inc()`` is only closed by ``x.dec()``,
    not by some other gauge's dec). ``anchor`` names a module-path suffix
    that defines the pair's home surface — AIL022 uses it to verify the
    declared symbols still resolve to real code whenever that module is in
    the scan (the AIL006 self-honesty trick: a rename must not silently
    disarm the rule). Specs with no anchor use verbs too generic to
    drift (``acquire``/``release``) and are exempt from AIL022."""

    name: str
    opens: tuple[str, ...]
    closes: tuple[str, ...]
    receiver: str = ""
    same_receiver: bool = False
    anchor: str = ""
    description: str = ""


#: The declarative pair table AIL020 enforces. Append-only by convention:
#: every row names the real bug class it encodes (docs/analysis.md has
#: the catalog row; docs/concurrency.md the conservation contract).
PAIR_SPECS: tuple[PairSpec, ...] = (
    PairSpec(
        name="estimator-inflight",
        opens=("begin",), closes=("end",),
        receiver=r"(orch|estimator)",
        anchor="orchestration/estimator.py",
        description="orchestration begin/end inflight accounting "
                    "(PR 7: RTTs observed without pairing)"),
    PairSpec(
        name="probe-slot",
        opens=("begin_probe",),
        closes=("record_success", "record_failure", "record_neutral"),
        anchor="resilience/breaker.py",
        description="breaker half-open probe slot take/settle "
                    "(PR 3: a vanished probe ejected a backend forever)"),
    PairSpec(
        name="limiter-slot",
        opens=("try_acquire", "acquire"), closes=("release",),
        description="limiter/semaphore/slot-pool acquire must be "
                    "released on every exit"),
    PairSpec(
        name="service-inflight",
        opens=("_reserve",), closes=("_release",),
        anchor="service/app.py",
        description="per-spec in-flight reservation (the reference "
                    "platform's concurrency accounting)"),
    PairSpec(
        name="gauge-updown",
        opens=("inc",), closes=("dec",), same_receiver=True,
        description="up-down gauge inc/dec — a leaked inc is permanent "
                    "phantom load"),
    PairSpec(
        name="drain-interlock",
        opens=("try_begin_reload",), closes=("end_reload",),
        anchor="rollout/drain.py",
        description="drain/reload interlock (PR 18: exactly-one-outcome "
                    "straggler retirement)"),
    PairSpec(
        name="ledger-buffer-flush",
        opens=("stamp",),
        closes=("flush", "drain", "_flush_ledger"),
        receiver=r"(buf|led)",
        anchor="observability/ledger.py",
        description="buffered hop-ledger stamps must flush on every "
                    "exit (PR 8: device failure dropped exactly the "
                    "failed tasks' stamps)"),
)


@dataclass(frozen=True)
class Escape:
    """One unbalanced open: ``kind`` is the exit class the close fails to
    cover. ``at_line`` is the escaping exit / abandoning await."""

    kind: str            # "return" | "raise" | "end" | "abandonment"
    spec: PairSpec
    open_line: int
    open_col: int
    open_snippet_node: ast.AST
    at_line: int
    receiver: str


_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
#: AST parents under which a node's execution is conditional even once
#: the enclosing statement is reached (used by the coverage check: a
#: close under one of these does not cover exits outside it).
_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)


def _chain(node: ast.AST) -> str | None:
    """Dotted receiver chain for Name/Attribute, else None (dynamic)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_scope(node: ast.AST, top: bool = True):
    """Walk ``node`` excluding nested function/lambda bodies — their
    calls open/close effects in their OWN frame, not this one."""
    if not top and isinstance(node, _NESTED):
        return
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _walk_scope(child, top=False)


def _match_call(node: ast.AST, verbs: tuple[str, ...]) -> str | None:
    """Receiver chain when ``node`` is a call of one of ``verbs``; the
    empty string for bare-name calls; None when it is not a match."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in verbs:
        return _chain(f.value) or "<dynamic>"
    if isinstance(f, ast.Name) and f.id in verbs:
        return ""
    return None


def _close_matches(open_chain: str, close_call: ast.Call,
                   close_chain: str, spec: PairSpec) -> bool:
    """Same receiver chain, or (non-strict pairs) the open's receiver is
    handed to the close as an argument — ``buf.stamp(...)`` is closed by
    ``self._flush_ledger(tm, task_id, buf)``."""
    if open_chain and close_chain == open_chain:
        return True
    if spec.same_receiver:
        return False
    if not open_chain and not close_chain:
        return True  # both bare names — module-level helpers
    root = open_chain.split(".")[0] if open_chain else ""
    args = list(close_call.args) + [k.value for k in close_call.keywords]
    for a in args:
        ch = _chain(a)
        if ch is None:
            continue
        if ch == open_chain or (root and root != "self" and ch == root):
            return True
    return False


def _lca(flow: AwaitFlow, a: ast.AST, b: ast.AST) -> ast.AST:
    bset = {id(n) for n in [b, *flow._ancestors(b)]}
    for n in [a, *flow._ancestors(a)]:
        if id(n) in bset:
            return n
    return flow.fn


def _arm_disjoint(flow: AwaitFlow, a: ast.AST, b: ast.AST) -> bool:
    """No single path executes both ``a`` and ``b``: different arms of an
    ``if``, different handlers of a ``try``, or handler vs ``orelse``."""
    for anc in flow._ancestors(a):
        if isinstance(anc, ast.If) and flow.in_subtree(b, anc):
            ba, bb = flow._branch_of(a, anc), flow._branch_of(b, anc)
            if (ba in ("body", "orelse") and bb in ("body", "orelse")
                    and ba != bb):
                return True
        if isinstance(anc, ast.Try) and flow.in_subtree(b, anc):
            ba, bb = flow._branch_of(a, anc), flow._branch_of(b, anc)
            if ba == "handlers" and bb == "handlers":
                ha = next((h for h in anc.handlers
                           if flow.in_subtree(a, h)), None)
                hb = next((h for h in anc.handlers
                           if flow.in_subtree(b, h)), None)
                if ha is not None and hb is not None and ha is not hb:
                    return True
            if {ba, bb} == {"handlers", "orelse"}:
                return True
    return False


def _unconditional_upto(flow: AwaitFlow, node: ast.AST,
                        stop: ast.AST) -> bool:
    """Once control enters ``stop``'s region on the straight-line path,
    does ``node`` always execute? False if any step strictly below
    ``stop`` is a branch arm, handler, loop body, short-circuit operand,
    or comprehension — i.e. anything the path can skip."""
    cur = node
    while cur is not stop:
        parent = flow._parent.get(cur)
        if parent is None or parent is stop:
            break
        if isinstance(parent, ast.If) and cur is not parent.test:
            return False
        if isinstance(parent, ast.IfExp) and cur is not parent.test:
            return False
        if isinstance(parent, ast.Try):
            branch = flow._branch_of(node, parent)
            if branch != "finalbody":
                return False  # body/handlers/orelse: skippable on the
                # exception (or no-exception) path
        if isinstance(parent, (ast.For, ast.AsyncFor, ast.While)):
            branch = flow._branch_of(node, parent)
            if branch in ("body", "orelse"):
                return False  # zero iterations / break
        if isinstance(parent, ast.BoolOp) and cur is not parent.values[0]:
            return False
        if isinstance(parent, _COMPREHENSIONS):
            return False
        cur = parent
    return True


def _covers_exit(flow: AwaitFlow, c: ast.AST, x: ast.AST) -> bool:
    """Every path from the region both share that reaches exit ``x``
    executed close ``c`` first. The walk checks ``c``'s side for
    skippable steps strictly below the common ancestor; the final step
    INTO the common ancestor is judged by which arms the two sit in
    (same ``try`` body vs handler differ from same plain block)."""
    lca = _lca(flow, c, x)
    if not _unconditional_upto(flow, c, lca):
        return False
    if isinstance(lca, ast.Try):
        bc, bx = flow._branch_of(c, lca), flow._branch_of(x, lca)
        if bc == "body" and bx in ("handlers", "finalbody"):
            return False  # the exception may fire before c runs
        if bc == "orelse" and bx in ("handlers", "finalbody"):
            return False
        if bc == "handlers" and bx == "finalbody":
            return False  # a different exception took a different arm
    if isinstance(lca, (ast.For, ast.AsyncFor, ast.While)):
        bc, bx = flow._branch_of(c, lca), flow._branch_of(x, lca)
        if bc == "body" and bx == "orelse":
            return False  # zero iterations reach orelse without c
    return True


def _reachable(flow: AwaitFlow, o: ast.AST, x: ast.AST) -> bool:
    """Can control reach ``x`` after executing ``o``? Prunes exits sealed
    off by a terminating tail: an except-handler that ends in ``return``
    cannot fall through to exits after its ``try``. Exception jumps are
    respected — an exit inside a handler or ``finally`` of an enclosing
    ``try`` stays reachable from inside that try's body. Exits positioned
    before the open (loop back edges) are out of scope here; the
    per-iteration end-escape check owns that path."""
    cur: ast.AST | None = _stmt_of(flow, o)
    normal = True  # can control still fall through normally?
    while cur is not None:
        parent = flow._parent.get(cur)
        if parent is None:
            return False
        if isinstance(parent, ast.Try):
            br = flow._branch_of(cur, parent)
            if br in ("body", "orelse") and any(
                    flow.in_subtree(x, h) for h in parent.handlers):
                return True  # an exception mid-tail jumps to the handler
            if br != "finalbody" and any(
                    flow.in_subtree(x, s) for s in parent.finalbody):
                return True
        block = _block_of(parent, cur) if isinstance(
            cur, (ast.stmt, ast.ExceptHandler)) else None
        if block is not None and normal:
            idx = next(i for i, s in enumerate(block) if s is cur)
            if any(flow.in_subtree(x, s) for s in block[idx + 1:]):
                return True
            if _terminates_block(block[idx:]):
                normal = False  # only exception propagation from here up
        cur = parent
    return False


def _reaches_fall_through(flow: AwaitFlow, o: ast.AST,
                          region: ast.AST) -> bool:
    """Whether the straight-line path from ``o`` can fall off the end of
    ``region`` (the function, or the open's enclosing loop body)."""
    cur: ast.AST | None = _stmt_of(flow, o)
    while cur is not None and cur is not region:
        parent = flow._parent.get(cur)
        if parent is None:
            break
        block = _block_of(parent, cur) if isinstance(
            cur, (ast.stmt, ast.ExceptHandler)) else None
        if block is not None:
            idx = next(i for i, s in enumerate(block) if s is cur)
            if _terminates_block(block[idx:]):
                return False
        cur = parent
    return True


def _stmt_of(flow: AwaitFlow, node: ast.AST) -> ast.stmt | None:
    """The innermost statement containing ``node`` whose parent is a
    block-carrying construct (so siblings can be enumerated)."""
    cur: ast.AST | None = node
    while cur is not None:
        parent = flow._parent.get(cur)
        if isinstance(cur, ast.stmt) and isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef, ast.If,
                         ast.For, ast.AsyncFor, ast.While, ast.With,
                         ast.AsyncWith, ast.Try, ast.ExceptHandler,
                         ast.Module)):
            return cur
        cur = parent
    return None


def _block_of(parent: ast.AST, stmt: ast.stmt) -> list[ast.stmt] | None:
    for _fname, value in ast.iter_fields(parent):
        if isinstance(value, list) and any(v is stmt for v in value):
            return value
    return None


def _terminates_block(stmts: list[ast.stmt]) -> bool:
    return bool(stmts) and _terminates(stmts[-1])


def _terminates(stmt: ast.stmt) -> bool:
    """Control cannot fall past ``stmt`` (syntactic approximation)."""
    if isinstance(stmt, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return True
    if isinstance(stmt, ast.If):
        return (bool(stmt.orelse) and _terminates_block(stmt.body)
                and _terminates_block(stmt.orelse))
    if isinstance(stmt, ast.Try):
        if stmt.finalbody and _terminates_block(stmt.finalbody):
            return True
        blocks = [stmt.orelse if stmt.orelse else stmt.body]
        blocks += [h.body for h in stmt.handlers]
        return all(_terminates_block(b) for b in blocks)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return _terminates_block(stmt.body)
    if isinstance(stmt, ast.While):
        infinite = (isinstance(stmt.test, ast.Constant)
                    and bool(stmt.test.value))
        return infinite and not any(isinstance(n, ast.Break)
                                    for n in ast.walk(stmt))
    return False


class _OpenAnalysis:
    """All matching/bless/escape logic for one (function, spec) pair."""

    def __init__(self, fn, spec: PairSpec, flow: AwaitFlow,
                 opens, closes, nested_closes):
        self.fn = fn
        self.spec = spec
        self.flow = flow
        self.opens = opens
        self.closes = closes
        self.nested_closes = nested_closes

    # -- blessed idioms ------------------------------------------------------

    def _cm_blessed(self, o: ast.Call) -> bool:
        return any(isinstance(a, ast.withitem)
                   for a in self.flow._ancestors(o))

    def _finally_blessed(self, o: ast.Call, matched: list[ast.Call]) -> bool:
        flow = self.flow
        for anc in flow._ancestors(o):
            if (isinstance(anc, ast.Try)
                    and flow._branch_of(o, anc) == "body"
                    and any(flow._branch_of(c, anc) == "finalbody"
                            for c in matched)):
                return True
        # Open immediately before a finally-protected try, separated only
        # by plain assignments (no awaits / exits in the gap — the gap is
        # where a cancellation would still leak). The anchor statement is
        # lifted through guard ``if``s: the pervasive
        #     if orch is not None: orch.begin(base)
        #     try: ... finally:
        #         if orch is not None: orch.end(base)
        # shape pairs a conditional open with an identically-guarded
        # close, and the interlock shape puts the open in the guard TEST
        # (``if not state.try_begin_reload(): return refusal``) with the
        # protected try as the next sibling.
        stmt = _stmt_of(flow, o)
        while stmt is not None:
            parent = flow._parent.get(stmt)
            block = _block_of(parent, stmt) if parent is not None else None
            if block:
                idx = next(i for i, s in enumerate(block) if s is stmt)
                for nxt in block[idx + 1:]:
                    if isinstance(nxt, ast.Try):
                        return any(flow._branch_of(c, nxt) == "finalbody"
                                   for c in matched)
                    if not isinstance(nxt, (ast.Assign, ast.AnnAssign)):
                        return False  # an exit/await in the gap leaks
                    if any(isinstance(n, ast.Await)
                           for n in ast.walk(nxt)):
                        return False
            if isinstance(parent, ast.If):
                stmt = parent
                continue
            break
        return False

    def _handoff_blessed(self, o: ast.Call) -> bool:
        flow = self.flow
        parent = flow._parent.get(o)
        if isinstance(parent, ast.Await):
            parent = flow._parent.get(parent)
        name = None
        if (isinstance(parent, ast.Assign) and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            name = parent.targets[0].id
        elif (isinstance(parent, ast.AnnAssign)
                and isinstance(parent.target, ast.Name)):
            name = parent.target.id
        if not name:
            return False

        def _mentions(node: ast.AST) -> bool:
            return any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(node))

        for node in _walk_scope(self.fn):
            if _pos(node) <= _pos(o):
                continue
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in node.targets):
                if _mentions(node.value) or any(_mentions(t)
                                                for t in node.targets):
                    return True
            if isinstance(node, ast.Return) and node.value is not None:
                if _mentions(node.value):
                    return True
        return False

    # -- escapes -------------------------------------------------------------

    def _covering_close(self, o: ast.Call, x: ast.AST,
                        matched: list[ast.Call]) -> ast.Call | None:
        """A matched close that every path from ``o`` to exit ``x``
        executes before leaving."""
        flow = self.flow
        for c in matched:
            if not (_pos(o) < _pos(c) < _pos(x)):
                continue
            if _arm_disjoint(flow, c, x) or _arm_disjoint(flow, o, c):
                continue
            if _covers_exit(flow, c, x):
                return c
        # A finally containing a matched close covers every exit inside
        # its try, even though the close is textually after the exit.
        for anc in flow._ancestors(x):
            if isinstance(anc, ast.Try) and flow._branch_of(x, anc) in (
                    "body", "handlers", "orelse"):
                for c in matched:
                    if (flow._branch_of(c, anc) == "finalbody"
                            and not _arm_disjoint(flow, o, c)):
                        return c
        return None

    def escapes_for(self, o: ast.Call, oc: str) -> list[Escape]:
        spec, flow = self.spec, self.flow
        matched = [c for c, cc in self.closes
                   if c is not o and _close_matches(oc, c, cc, spec)]
        matched_nested = [c for c, cc in self.nested_closes
                          if _close_matches(oc, c, cc, spec)]
        if not matched and not matched_nested:
            return []  # cross-function protocol endpoint — out of scope
        if matched_nested:
            return []  # callback handoff: the close rides another frame
        if (self._cm_blessed(o) or self._finally_blessed(o, matched)
                or self._handoff_blessed(o)):
            return []

        out: list[Escape] = []

        def esc(kind: str, at: ast.AST) -> Escape:
            return Escape(kind=kind, spec=spec, open_line=o.lineno,
                          open_col=o.col_offset, open_snippet_node=o,
                          at_line=getattr(at, "lineno", o.lineno),
                          receiver=oc)

        for node in _walk_scope(self.fn):
            if not isinstance(node, (ast.Return, ast.Raise)):
                continue
            if _pos(node) <= _pos(o):
                continue
            if _arm_disjoint(flow, o, node):
                continue
            if not _reachable(flow, o, node):
                continue
            if self._covering_close(o, node, matched) is None:
                kind = "return" if isinstance(node, ast.Return) else "raise"
                out.append(esc(kind, node))

        out.extend(self._end_escape(o, matched, esc))
        if not out:
            out.extend(self._abandonment(o, matched, esc))
        return out

    def _end_escape(self, o: ast.Call, matched: list[ast.Call],
                    esc) -> list[Escape]:
        """Falling off the end of the function — or, for an open inside a
        loop, reaching the end of the iteration — without an
        unconditional close."""
        flow = self.flow
        loops = flow._enclosing_loops(o)
        if loops:
            region = loops[0]  # innermost: the per-iteration lifecycle
        else:
            if _terminates_block(self.fn.body):
                return []
            region = self.fn
        if not _reaches_fall_through(flow, o, region):
            return []  # the open's own tail always exits explicitly
        for c in matched:
            if _pos(c) <= _pos(o):
                continue
            if not flow.in_subtree(c, region):
                continue
            if _arm_disjoint(flow, o, c):
                continue
            if _unconditional_upto(flow, c, region):
                return []
        # A finally-close anywhere up o's ancestry inside the region also
        # closes the straight-line path.
        for anc in flow._ancestors(o):
            if not flow.in_subtree(anc, region):
                break
            if isinstance(anc, ast.Try) and any(
                    flow._branch_of(c, anc) == "finalbody"
                    for c in matched):
                return []
        tail = region.body[-1] if getattr(region, "body", None) else o
        return [esc("end", tail)]

    def _abandonment(self, o: ast.Call, matched: list[ast.Call],
                     esc) -> list[Escape]:
        """Every exit is covered by a plain (non-finally) close — but an
        await between the open and that close abandons the frame on
        cancellation, and the close never runs."""
        if not isinstance(self.fn, ast.AsyncFunctionDef):
            return []
        flow = self.flow
        candidates = sorted(
            (c for c in matched
             if _pos(c) > _pos(o) and not _arm_disjoint(flow, o, c)),
            key=_pos)
        if not candidates:
            return []
        first = candidates[0]
        sus = flow.suspensions_between(flow.lift_to_await(o),
                                       flow.lift_to_await(first))
        if sus:
            return [esc("abandonment", sus[0])]
        return []


#: Compiled receiver patterns, one per spec (module-load cost, not
#: per-function).
_RECEIVER_RX = {s.name: re.compile(s.receiver) if s.receiver else None
                for s in PAIR_SPECS}
_ALL_VERBS = frozenset(v for s in PAIR_SPECS for v in (*s.opens, *s.closes))


def check_all(fn, specs: tuple[PairSpec, ...] = PAIR_SPECS
              ) -> list[Escape]:
    """All unbalanced opens of every spec inside ``fn`` (one frame only;
    nested defs are separate frames the caller visits independently).
    One AST walk collects every candidate call; the CFG is built only
    when some spec has both sides present — the whole-repo scan's cost
    is dominated by functions that open nothing."""
    calls: list[tuple[ast.Call, str, str]] = []   # (node, verb, chain)
    for node in _walk_scope(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        verb = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if verb is None or verb not in _ALL_VERBS:
            continue
        chain = (_chain(f.value) or "<dynamic>"
                 if isinstance(f, ast.Attribute) else "")
        calls.append((node, verb, chain))
    if not calls:
        return []
    nested: list[tuple[ast.Call, str, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, _NESTED) and node is not fn:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                verb = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else None)
                if verb is None or verb not in _ALL_VERBS:
                    continue
                chain = (_chain(f.value) or "<dynamic>"
                         if isinstance(f, ast.Attribute) else "")
                nested.append((sub, verb, chain))

    flow: AwaitFlow | None = None
    out: list[Escape] = []
    for spec in specs:
        if fn.name in spec.opens or fn.name in spec.closes:
            continue  # the pair's own shim/wrapper — it IS one side
        rx = _RECEIVER_RX.get(spec.name)
        if rx is None and spec.receiver:
            rx = re.compile(spec.receiver)
        opens = [(n, c) for n, v, c in calls
                 if v in spec.opens and (rx is None or rx.search(c))]
        if not opens:
            continue
        closes = [(n, c) for n, v, c in calls if v in spec.closes]
        nested_closes = [(n, c) for n, v, c in nested
                         if v in spec.closes]
        if not closes and not nested_closes:
            continue
        if flow is None:
            flow = AwaitFlow(fn)
        analysis = _OpenAnalysis(fn, spec, flow, opens, closes,
                                 nested_closes)
        for o, oc in opens:
            out.extend(analysis.escapes_for(o, oc))
    return out


def check_function(fn, spec: PairSpec,
                   flow: AwaitFlow | None = None) -> list[Escape]:
    """Single-spec entry point (tests, targeted audits)."""
    return check_all(fn, (spec,))
