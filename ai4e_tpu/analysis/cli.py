"""``python -m ai4e_tpu.analysis`` — the CI gate entrypoint.

Exit codes: 0 clean (baselined findings allowed), 1 non-baselined
findings, 2 configuration error (unreadable baseline, entry without a
justification, git failure under --changed-only), 4 wall-time budget
exceeded (--budget-ms). Stdlib-only: the gate runs without the JAX
toolchain.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .core import Analyzer, Baseline, BaselineError, ProjectRule
from .rules import ALL_RULES

DEFAULT_BASELINE = "analysis_baseline.json"

_FAMILY_TITLES = {
    "invariants": "intra-process invariants",
    "wire": "wire contracts (cross-process)",
    "balance": "paired-effect conservation",
    "hygiene": "analyzer hygiene",
}

_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def _sarif_document(result, rules) -> dict:
    """SARIF 2.1.0 for the run's ACTIVE findings (baselined ones are
    accepted debt, not annotations). The baseline fingerprint doubles as
    ``partialFingerprints`` — same identity, so an annotation survives
    pushes that merely move the finding, exactly like the baseline does.
    Schema documented in docs/analysis.md beside --json v1."""
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "ai4e-lint",
                "informationUri": "docs/analysis.md",
                "rules": [{
                    "id": r.rule_id,
                    "name": r.name,
                    "shortDescription": {"text": r.description},
                } for r in rules],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                }}],
                "partialFingerprints": {
                    "ai4eFingerprint/v1": f.fingerprint},
            } for f in result.findings],
        }],
    }


def _print_stats(result, stream) -> None:
    print(f"stats: {result.files_scanned} file(s) parsed in "
          f"{result.parse_seconds * 1000:.0f} ms, total "
          f"{result.total_seconds * 1000:.0f} ms", file=stream)
    for rule_id, secs in sorted(result.rule_seconds.items(),
                                key=lambda kv: -kv[1]):
        print(f"stats: {rule_id}  {secs * 1000:8.1f} ms", file=stream)


def _over_budget(budget_ms, result) -> bool:
    """True (and a loud stderr line) when the run blew its wall-time
    budget. Exit 4 so CI distinguishes 'slow' from 'findings' (1) and
    'misconfigured' (2)."""
    if budget_ms is None:
        return False
    total = result.total_seconds * 1000
    if total <= budget_ms:
        return False
    print(f"error: analyzer wall time {total:.0f} ms exceeds --budget-ms "
          f"{budget_ms} — profile with --stats and trim the slowest "
          "rules, or raise the documented budget in docs/analysis.md",
          file=sys.stderr)
    return True


class ChangedOnlyError(RuntimeError):
    """git could not produce the changed-file set. A configuration error
    (exit 2): a broken ref in the pre-commit hook must fail loudly, not
    silently scan nothing and pass."""


def changed_py_files(root: str, ref: str) -> list[str]:
    """Repo-root-relative ``.py`` paths changed vs ``ref`` plus untracked
    ones — the pre-commit working set. Deleted files are filtered out
    (nothing to scan)."""
    def _git(*args: str) -> list[str]:
        proc = subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
            timeout=30)
        if proc.returncode != 0:
            raise ChangedOnlyError(
                f"git {' '.join(args)} failed (exit {proc.returncode}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        return [ln for ln in proc.stdout.splitlines() if ln.strip()]

    seen: dict[str, None] = {}
    for rel in (_git("diff", "--name-only", ref, "--")
                + _git("ls-files", "--others", "--exclude-standard")):
        if rel.endswith(".py") and rel not in seen:
            if os.path.exists(os.path.join(root, rel)):
                seen[rel] = None
    return list(seen)


class UnknownRuleError(ValueError):
    """``--select``/``--ignore`` named a rule id the catalog doesn't have.
    A configuration error (exit 2), NOT an empty-selection no-op: a typo'd
    id in the CI job must fail the gate loudly, not silently disable it."""


def _build_rules(select: str | None, ignore: str | None):
    rules = [cls() for cls in ALL_RULES]
    catalog = {r.rule_id for r in rules}

    def _ids(raw: str, flag: str) -> set[str]:
        ids = {r.strip().upper() for r in raw.split(",") if r.strip()}
        unknown = sorted(ids - catalog)
        if unknown:
            raise UnknownRuleError(
                f"{flag} names unknown rule id(s): {', '.join(unknown)} "
                f"(catalog: {', '.join(sorted(catalog))})")
        return ids

    if select:
        wanted = _ids(select, "--select")
        rules = [r for r in rules if r.rule_id in wanted]
    if ignore:
        dropped = _ids(ignore, "--ignore")
        rules = [r for r in rules if r.rule_id not in dropped]
    return rules


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ai4e_tpu.analysis",
        description="ai4e-lint: AST-based platform-invariant analyzer "
                    "(docs/analysis.md)")
    parser.add_argument("paths", nargs="*", default=["ai4e_tpu"],
                        help="files/directories to analyze "
                             "(default: ai4e_tpu)")
    parser.add_argument("--root", default=None,
                        help="repo root for relative paths and the docs/ "
                             "surface AIL006 correlates against "
                             "(default: cwd)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: {DEFAULT_BASELINE} "
                             "under --root when it exists)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current findings to the baseline file "
                             "with EMPTY justifications (the next run "
                             "refuses the file until each is filled in)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--sarif", action="store_true",
                        help="SARIF 2.1.0 output (active findings only; "
                             "fingerprints ride partialFingerprints so "
                             "PR annotations dedupe across pushes)")
    parser.add_argument("--stats", action="store_true",
                        help="print per-rule wall time after the run "
                             "(stderr in text mode, `stats` key in "
                             "--json)")
    parser.add_argument("--changed-only", nargs="?", const="origin/main",
                        default=None, metavar="REF",
                        help="scope the scan to .py files changed vs a "
                             "git ref (default ref: origin/main) plus "
                             "untracked ones; project-wide rules are "
                             "skipped — CI keeps the whole-repo gate")
    parser.add_argument("--budget-ms", type=int, default=None, metavar="N",
                        help="fail with exit 4 if total analyzer wall "
                             "time exceeds N milliseconds — keeps the "
                             "blocking CI job from decaying as rules "
                             "accumulate")
    parser.add_argument("--select", default=None, metavar="IDS",
                        help="comma-separated rule ids to run")
    parser.add_argument("--ignore", default=None, metavar="IDS",
                        help="comma-separated rule ids to skip")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--dump-wire", action="store_true",
                        help="print docs/API.md's ai4e:routes / "
                             "ai4e:headers marked tables generated from "
                             "the extracted wire surface, and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        # Family group headers deliberately do NOT start with "AIL":
        # scripts/lint.sh counts rules with `grep -c '^AIL'` and an
        # AIL-prefixed banner would inflate the registry count it gates.
        last_family = None
        for cls in ALL_RULES:
            family = getattr(cls, "family", "invariants")
            if family != last_family:
                print(f"# {_FAMILY_TITLES.get(family, family)}")
                last_family = family
            print(f"{cls.rule_id}  {cls.name:<26} {cls.description}")
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    abs_paths = [os.path.join(root, p) if not os.path.isabs(p) else p
                 for p in args.paths]

    if args.dump_wire:
        from .core import ProjectContext, _iter_py_files, parse_module
        from .rules.wire import dump_wire
        modules = []
        for path in _iter_py_files(abs_paths):
            rel = os.path.relpath(os.path.abspath(path), root)
            try:
                modules.append(parse_module(path, rel.replace(os.sep, "/")))
            except (OSError, SyntaxError, ValueError):
                continue
        print(dump_wire(root, ProjectContext(root=root, modules=modules)),
              end="")
        return 0

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    baseline = Baseline()
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = Baseline.load(baseline_path)
        except BaselineError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2

    try:
        rules = _build_rules(args.select, args.ignore)
    except UnknownRuleError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    if args.changed_only is not None:
        try:
            rels = changed_py_files(root, args.changed_only)
        except (ChangedOnlyError, OSError,
                subprocess.SubprocessError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        scoped = []
        for rel in rels:
            ap = os.path.join(root, rel)
            if any(ap == base
                   or ap.startswith(base.rstrip(os.sep) + os.sep)
                   for base in abs_paths):
                scoped.append(ap)
        # Project-wide rules correlate the WHOLE tree (docs surfaces,
        # wire contracts, journal round-trip); on a file slice they
        # would report nonsense one-sided drift. CI's full run keeps
        # them armed.
        rules = [r for r in rules if not isinstance(r, ProjectRule)]
        if not scoped:
            print(f"ai4e-lint: no changed .py files vs "
                  f"{args.changed_only} in scope; nothing to scan")
            return 0
        abs_paths = scoped

    analyzer = Analyzer(rules, root=root, baseline=baseline)
    result = analyzer.run(abs_paths)

    if args.write_baseline:
        Baseline.write(baseline_path, result.findings)
        print(f"wrote {len(result.findings)} finding(s) to {baseline_path}; "
              "fill in every justification before committing")
        return 0

    if args.sarif:
        print(json.dumps(_sarif_document(result, rules), indent=2))
        if args.stats:
            _print_stats(result, sys.stderr)
        if _over_budget(args.budget_ms, result):
            return 4
        return 1 if result.findings else 0

    if args.as_json:
        # Schema documented in docs/analysis.md ("--json output"). Each
        # finding carries its baseline fingerprint AND a ready-to-paste
        # ``baseline_entry`` (justification left empty — a human writes
        # it), so baselines are authored/audited from this output instead
        # of re-deriving fingerprints by hand.
        def _dump(f):
            d = f.to_dict()
            d["baseline_entry"] = {
                "rule": f.rule, "path": f.path, "symbol": f.symbol,
                "snippet": f.snippet, "fingerprint": f.fingerprint,
                "justification": "",
            }
            return d
        doc = {
            "version": 1,
            "findings": [_dump(f) for f in result.findings],
            "baselined": [f.to_dict() for f in result.baselined],
            "suppressed": result.suppressed,
            "stale_baseline": result.stale_baseline,
            "files_scanned": result.files_scanned,
        }
        if args.stats:
            doc["stats"] = {
                "parse_seconds": result.parse_seconds,
                "total_seconds": result.total_seconds,
                "rule_seconds": result.rule_seconds,
            }
        print(json.dumps(doc, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        for e in result.stale_baseline:
            print(f"warning: stale baseline entry {e.get('fingerprint')} "
                  f"({e.get('rule')} in {e.get('path')}) — finding no "
                  "longer exists; remove it", file=sys.stderr)
        n = len(result.findings)
        print(f"ai4e-lint: {result.files_scanned} file(s), {n} finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{result.suppressed} suppressed")
        if args.stats:
            _print_stats(result, sys.stderr)
    if _over_budget(args.budget_ms, result):
        return 4
    return 1 if result.findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
