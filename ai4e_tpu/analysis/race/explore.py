"""``explore_interleavings`` — the pytest-facing exploration harness.

Usage (see ``tests/test_race_regressions.py`` for the platform suite)::

    def make():
        store = InMemoryTaskStore()            # FRESH state per schedule
        tm = TracedTaskManager(LocalTaskManager(store))
        ...build the competing coroutines...
        def check():                            # post-run invariant
            assert store.get(tid).canonical_status == "completed"
        return [coro_a(), coro_b()], check

    report = explore_interleavings(make, schedules=60, seed=20260803)
    assert report.ok, report.describe()

Exploration strategy — bounded-systematic first, seeded-random for the
rest of the budget:

- **systematic**: breadth-first over scheduling-decision prefixes. Run
  the all-first-choice schedule, then for every decision point where
  ``n`` callbacks were runnable, branch each untaken choice into a new
  prefix; repeat until the budget's systematic share is spent. Shallow
  divergences (where check-then-act races live — the competitor slotting
  into the first few windows) are covered exhaustively;
- **random**: ``random.Random(seed*1000003 + i)`` per remaining run —
  deep/late interleavings the bounded frontier can't reach.

Same ``(schedules, seed)`` → the same schedule set in the same order →
the same verdict, on any machine: schedules never consult wall clock,
and the virtual loop jumps time instead of sleeping.

A run FAILS when a vthread raises, the post-run ``check`` raises, the
scheduler deadlocks, or the step budget trips. The report carries each
failure's schedule trace — paste it into ``PrefixSchedule`` to replay
that exact interleaving under a debugger.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .scheduler import (DeadlockError, PrefixSchedule, RandomSchedule,
                        ScheduleBudgetExceeded, VirtualLoop)


@dataclass
class RunResult:
    schedule_id: int
    kind: str                     # "systematic" | "random"
    trace: list = field(default_factory=list)
    error: BaseException | None = None

    @property
    def ok(self) -> bool:
        return self.error is None


@dataclass
class ExplorationReport:
    runs: list[RunResult]
    seed: int

    @property
    def failures(self) -> list[RunResult]:
        return [r for r in self.runs if not r.ok]

    @property
    def ok(self) -> bool:
        return not self.failures

    def describe(self) -> str:
        if self.ok:
            return (f"{len(self.runs)} schedules explored (seed "
                    f"{self.seed}), no violation")
        lines = [f"{len(self.failures)}/{len(self.runs)} schedules "
                 f"violated (seed {self.seed}); first:"]
        first = self.failures[0]
        lines.append(f"  schedule #{first.schedule_id} ({first.kind}), "
                     f"replay prefix: {[c for c, _ in first.trace]}")
        lines.append(f"  {type(first.error).__name__}: {first.error}")
        return "\n".join(lines)


def _one_run(make_coros, schedule, max_steps: int) -> BaseException | None:
    made = make_coros()
    if (isinstance(made, tuple) and len(made) == 2 and callable(made[1])):
        coros, check = made
    else:
        coros, check = made, None
    loop = VirtualLoop(schedule, max_steps=max_steps)
    try:
        results = loop.run(list(coros))
    except (DeadlockError, ScheduleBudgetExceeded) as exc:
        return exc
    for r in results:
        if isinstance(r, BaseException):
            return r
    # Background tasks the explored code spawned are part of the verdict:
    # a crash in one must fail the run, not pass silently because no root
    # awaited it.
    if loop.background_errors:
        return loop.background_errors[0]
    if check is not None:
        try:
            check()
        except BaseException as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — not swallowed: the exception IS the run's verdict, returned into the report
            return exc
    return None


def explore_interleavings(make_coros, schedules: int = 50, seed: int = 0,
                          systematic: int | None = None,
                          max_steps: int = 20_000,
                          fail_fast: bool = False) -> ExplorationReport:
    """Run ``make_coros`` under up to ``schedules`` deterministic
    interleavings (module docstring). ``make_coros()`` must build FRESH
    coroutines AND fresh shared state each call, returning either a list
    of coroutines or ``(coroutines, check)`` where ``check()`` asserts
    the post-run invariant. ``systematic`` bounds the breadth-first
    prefix share (default: half the budget). ``fail_fast`` stops at the
    first violating schedule — regression tests usually want the full
    count, minimization wants the first.
    """
    if systematic is None:
        systematic = schedules // 2
    runs: list[RunResult] = []
    seen_traces: set[tuple] = set()
    run_id = 0

    frontier: deque[list[int]] = deque([[]])
    while frontier and run_id < min(systematic, schedules):
        prefix = frontier.popleft()
        sched = PrefixSchedule(prefix)
        error = _one_run(make_coros, sched, max_steps)
        trace = sched.trace
        key = tuple(c for c, _ in trace)
        if key in seen_traces and error is None:
            continue  # a shrunken prefix converged on a covered path
        seen_traces.add(key)
        runs.append(RunResult(run_id, "systematic", trace, error))
        run_id += 1
        if error is not None and fail_fast:
            return ExplorationReport(runs, seed)
        # Branch every untaken choice past this prefix's forced part.
        for i in range(len(prefix), len(trace)):
            _, n = trace[i]
            for alt in range(1, n):
                frontier.append([c for c, _ in trace[:i]] + [alt])

    while run_id < schedules:
        sched = RandomSchedule(seed * 1000003 + run_id)
        error = _one_run(make_coros, sched, max_steps)
        runs.append(RunResult(run_id, "random", sched.trace, error))
        run_id += 1
        if error is not None and fail_fast:
            break
    return ExplorationReport(runs, seed)
