"""Deterministic cooperative scheduler — a virtual-clock event loop whose
every scheduling decision is made by an explicit ``Schedule``.

Why a custom loop instead of instrumenting coroutines: asyncio's own
ready queue is FIFO, so for a fixed program it always explores exactly ONE
interleaving — the racy window between a guard and its write is only ever
hit when wall-clock jitter happens to land a competing callback in the
gap (which is precisely why the PR 3/PR 4 races survived until a seeded
chaos run stumbled into them). Here the ready queue is the decision
surface: whenever more than one callback is runnable, the ``Schedule``
picks which runs next. Every task step, future completion and timer is a
callback, so the schedule controls ordering at every yield point of every
explored coroutine — including awaits buried arbitrarily deep in platform
code, with zero instrumentation of the code under test.

Determinism:

- the ready queue is insertion-ordered and popped by schedule choice;
- timers live in a heap keyed ``(when, seq)`` — ties break by creation
  order;
- the clock is virtual: when nothing is ready, time JUMPS to the next
  timer. ``asyncio.sleep(30)`` in explored code costs nothing and two
  runs with the same schedule are byte-identical.

The loop implements the subset of the event-loop surface that
``asyncio``'s task/future/sleep/lock/event/gather machinery actually
calls (``call_soon`` / ``call_later`` / ``call_at`` / ``time`` /
``create_future`` / ``create_task`` / ``get_debug`` / …). It is NOT a
general replacement loop — it exists to be driven by ``run_schedule``.
"""

from __future__ import annotations

import asyncio
import heapq
import random


class DeadlockError(RuntimeError):
    """Every explored coroutine is blocked and no timer is pending — a
    genuine deadlock (e.g. a lock cycle) in the explored code."""


class ScheduleBudgetExceeded(RuntimeError):
    """The run exceeded ``max_steps`` callbacks — explored code is looping
    (or legitimately needs a bigger budget)."""


class _Handle:
    """Minimal Handle/TimerHandle: what Task/Future/sleep call on us."""

    __slots__ = ("_callback", "_args", "_context", "_cancelled", "_when")

    def __init__(self, callback, args, context=None, when=None):
        self._callback = callback
        self._args = args
        self._context = context
        self._cancelled = False
        self._when = when

    def cancel(self) -> None:
        self._cancelled = True

    def cancelled(self) -> bool:
        return self._cancelled

    def when(self) -> float:
        return self._when or 0.0

    def _run(self) -> None:
        if self._context is not None:
            self._context.run(self._callback, *self._args)
        else:
            self._callback(*self._args)


class RandomSchedule:
    """Seeded random scheduling decisions; the trace records every
    ``(choice, n_runnable)`` so a run can be replayed or minimized."""

    def __init__(self, seed: int):
        self.seed = seed
        self._rng = random.Random(seed)
        self.trace: list[tuple[int, int]] = []

    def pick(self, n: int) -> int:
        choice = self._rng.randrange(n)
        self.trace.append((choice, n))
        return choice


class PrefixSchedule:
    """Replay forced choices, then always pick 0 — the unit of systematic
    exploration: the explorer enumerates divergence prefixes and this
    schedule realizes each one deterministically."""

    def __init__(self, prefix: list[int] | tuple[int, ...] = ()):
        self.prefix = list(prefix)
        self.trace: list[tuple[int, int]] = []

    def pick(self, n: int) -> int:
        k = len(self.trace)
        choice = self.prefix[k] if k < len(self.prefix) else 0
        if choice >= n:
            choice = n - 1  # branching factor shrank on this path
        self.trace.append((choice, n))
        return choice


class VirtualLoop:
    """The virtual-clock, schedule-driven event loop (module docstring)."""

    def __init__(self, schedule, max_steps: int = 20_000):
        self._schedule = schedule
        self._max_steps = max_steps
        self._ready: list[_Handle] = []
        self._timers: list[tuple[float, int, _Handle]] = []
        self._time = 0.0
        self._seq = 0
        self._steps = 0
        self._tasks: list[asyncio.Task] = []
        self.exceptions: list[dict] = []  # call_exception_handler records
        # Exceptions from BACKGROUND tasks the explored code spawned
        # (create_task and forgot, or was still awaiting when the roots
        # finished). Collected by run(); a verdict surface — the explorer
        # fails the run on them, else a crash in a spawned task would pass
        # silently (roots are reported via their own results).
        self.background_errors: list[BaseException] = []

    # -- the event-loop surface asyncio machinery calls ---------------------

    def time(self) -> float:
        return self._time

    def call_soon(self, callback, *args, context=None) -> _Handle:
        h = _Handle(callback, args, context)
        self._ready.append(h)
        return h

    # publish()-style callers hop threads in production; under the
    # explorer everything is one thread, so threadsafe == soon.
    call_soon_threadsafe = call_soon

    def call_later(self, delay, callback, *args, context=None) -> _Handle:
        return self.call_at(self._time + max(0.0, delay), callback, *args,
                            context=context)

    def call_at(self, when, callback, *args, context=None) -> _Handle:
        h = _Handle(callback, args, context, when=when)
        self._seq += 1
        heapq.heappush(self._timers, (when, self._seq, h))
        return h

    def create_future(self) -> asyncio.Future:
        return asyncio.Future(loop=self)

    def create_task(self, coro, *, name=None, context=None) -> asyncio.Task:
        # Deterministic per-loop names: RaceTracker reports and replay
        # traces must read identically across runs (the global Task-N
        # counter depends on everything run before).
        kwargs = {"loop": self,
                  "name": name or f"vthread-{len(self._tasks)}"}
        if context is not None:
            kwargs["context"] = context
        task = asyncio.Task(coro, **kwargs)
        self._tasks.append(task)
        return task

    def get_debug(self) -> bool:
        return False

    def is_running(self) -> bool:
        return True

    def is_closed(self) -> bool:
        return False

    def call_exception_handler(self, context: dict) -> None:
        self.exceptions.append(context)

    # asyncio.Future.__del__ consults the loop's default handler path via
    # call_exception_handler only — nothing else to implement.

    # -- driving -------------------------------------------------------------

    def _advance(self) -> None:
        """Nothing ready: jump virtual time to the next timer deadline and
        move every timer due at that instant to the ready queue."""
        while self._timers and self._timers[0][2].cancelled():
            heapq.heappop(self._timers)
        if not self._timers:
            raise DeadlockError(
                "all explored coroutines are blocked and no timer is "
                "pending — deadlock in the explored code")
        when, _, h = heapq.heappop(self._timers)
        self._time = max(self._time, when)
        self._ready.append(h)
        while self._timers and self._timers[0][0] <= self._time:
            _, _, h2 = heapq.heappop(self._timers)
            if not h2.cancelled():
                self._ready.append(h2)

    def _run_once(self) -> None:
        while True:
            if not self._ready:
                self._advance()
            n = len(self._ready)
            idx = self._schedule.pick(n) if n > 1 else 0
            handle = self._ready.pop(idx)
            if handle.cancelled():
                continue
            self._steps += 1
            if self._steps > self._max_steps:
                raise ScheduleBudgetExceeded(
                    f"run exceeded {self._max_steps} scheduler steps")
            handle._run()
            return

    def run(self, coros) -> list:
        """Drive ``coros`` (top-level vthreads) to completion under the
        schedule; returns each one's result or exception (``gather``-style
        ``return_exceptions`` shape, so one vthread's crash doesn't hide
        the others' outcomes)."""
        prev = asyncio.events._get_running_loop()
        asyncio.events._set_running_loop(self)
        try:
            roots = [self.create_task(c) for c in coros]
            while not all(t.done() for t in roots):
                self._run_once()
            # Let background tasks the explored code spawned finish (or
            # fail) so their effects are part of the run's verdict; then
            # reap stragglers so no pending-task warnings leak between
            # runs.
            settle = 0
            while (any(not t.done() for t in self._tasks)
                   and settle < self._max_steps):
                settle += 1
                try:
                    self._run_once()
                except DeadlockError:
                    break
            for t in self._tasks:
                if not t.done():
                    t.cancel()
            settle = 0
            while (any(not t.done() for t in self._tasks)
                   and settle < 1000):
                settle += 1
                try:
                    self._run_once()
                except DeadlockError:
                    break
            roots_set = set(map(id, roots))
            for t in self._tasks:
                # Retrieve background failures NOW: unconsumed task
                # exceptions otherwise surface only at GC time (or never),
                # and the run's verdict must include them. Reap-phase
                # cancellations are ours, not the explored code's.
                if (id(t) not in roots_set and t.done()
                        and not t.cancelled()
                        and t.exception() is not None):
                    self.background_errors.append(t.exception())
            out = []
            for t in roots:
                if t.cancelled():
                    out.append(asyncio.CancelledError())
                elif t.exception() is not None:
                    out.append(t.exception())
                else:
                    out.append(t.result())
            return out
        finally:
            asyncio.events._set_running_loop(prev)


def run_schedule(make_coros, schedule, max_steps: int = 20_000):
    """One deterministic run: fresh coroutines (and fresh shared state —
    ``make_coros`` must build both) under ``schedule``. Returns
    ``(results, schedule.trace)``."""
    loop = VirtualLoop(schedule, max_steps=max_steps)
    return loop.run(make_coros()), schedule.trace
