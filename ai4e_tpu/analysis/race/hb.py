"""Vector-clock happens-before tracking over instrumented shared state.

Single-threaded asyncio has no *data* races — each segment between
suspension points is atomic — but it is full of *schedule* races: two
coroutines touching the same logical state where at least one writes and
neither access causally precedes the other, so a different interleaving
produces a different outcome (the stale-guard clobbers AIL007 encodes).
This module detects exactly those pairs:

- every explored coroutine (vthread) carries a **vector clock**, bumped
  on each recorded access;
- synchronization edges come from the traced primitives — releasing a
  ``TracedLock`` / setting a ``TracedEvent`` publishes the releaser's
  clock, acquiring/waiting absorbs it;
- two accesses to the same variable, at least one a write, with neither
  clock ≤ the other, are a **racy pair** — reported with both stack
  traces so the finding names the two code paths, not just the variable.

Instrumentation is explicit (wrap the state you care about) — this is a
test harness, not a global tracer. ``TracedTaskManager`` wraps any
``TaskManagerBase``-shaped manager, inserting one ``yield_point()``
before each store operation: the suspension every REAL deployment has
(the store is an HTTP hop away), without which an in-process
``LocalTaskManager``'s guard+write would execute atomically and the
explorer could never interleave the window under test.
"""

from __future__ import annotations

import asyncio
import traceback


class _YieldOnce:
    def __await__(self):
        yield


async def yield_point() -> None:
    """One suspension point: hands the scheduler a decision, nothing else.
    (``asyncio.sleep(0)`` without the asyncio import ceremony — and
    grep-able as explicit race-window instrumentation.)"""
    await _YieldOnce()


class RaceError(AssertionError):
    """Raised by ``RaceTracker.assert_race_free`` — carries the racy pairs."""

    def __init__(self, pairs):
        self.pairs = pairs
        super().__init__(
            f"{len(pairs)} racy access pair(s):\n\n" + "\n\n".join(
                f"--- {a.kind} vs {b.kind} on {a.var!r} "
                f"({a.vthread} / {b.vthread}) ---\n"
                f"{a.vthread} {a.kind}:\n{a.stack}\n"
                f"{b.vthread} {b.kind}:\n{b.stack}"
                for a, b in pairs))


class Access:
    __slots__ = ("var", "kind", "vthread", "clock", "stack")

    def __init__(self, var, kind, vthread, clock, stack):
        self.var = var
        self.kind = kind          # "read" | "write"
        self.vthread = vthread    # task name
        self.clock = clock        # dict vthread -> int, snapshot
        self.stack = stack        # rendered stack trace (str)

    def happens_before(self, other: "Access") -> bool:
        return self.clock.get(self.vthread, 0) <= other.clock.get(
            self.vthread, 0)


class RaceTracker:
    """Collects accesses, maintains clocks, reports racy pairs."""

    def __init__(self, stack_depth: int = 12):
        self.stack_depth = stack_depth
        self._clocks: dict[str, dict[str, int]] = {}
        self._accesses: dict[str, list[Access]] = {}
        self.races: list[tuple[Access, Access]] = []
        self._seen_pairs: set[tuple] = set()

    # -- vthread identity / clocks ------------------------------------------

    def _vthread(self) -> str:
        task = asyncio.current_task()
        return task.get_name() if task is not None else "<no-task>"

    def _clock_of(self, vthread: str) -> dict[str, int]:
        c = self._clocks.get(vthread)
        if c is None:
            c = self._clocks[vthread] = {}
        return c

    def _stack(self) -> str:
        frames = traceback.extract_stack()
        # Drop the tracker's own frames (this fn + the record caller).
        frames = frames[:-2][-self.stack_depth:]
        return "".join(traceback.format_list(frames)).rstrip()

    # -- access recording -----------------------------------------------------

    def record(self, var: str, kind: str) -> None:
        vthread = self._vthread()
        clock = self._clock_of(vthread)
        clock[vthread] = clock.get(vthread, 0) + 1
        acc = Access(var, kind, vthread, dict(clock), self._stack())
        for prev in self._accesses.setdefault(var, []):
            if prev.vthread == vthread:
                continue
            if prev.kind == "read" and kind == "read":
                continue
            if prev.happens_before(acc) or acc.happens_before(prev):
                continue
            key = (prev.vthread, prev.clock.get(prev.vthread, 0),
                   vthread, clock[vthread], var)
            if key not in self._seen_pairs:
                self._seen_pairs.add(key)
                self.races.append((prev, acc))
        self._accesses[var].append(acc)

    def read(self, var: str) -> None:
        self.record(var, "read")

    def write(self, var: str) -> None:
        self.record(var, "write")

    # -- synchronization edges ------------------------------------------------

    def publish(self, sync_clock: dict[str, int]) -> None:
        """Release side: fold the current vthread's clock into the sync
        object's clock (lock release, event set)."""
        vthread = self._vthread()
        clock = self._clock_of(vthread)
        clock[vthread] = clock.get(vthread, 0) + 1
        for k, v in clock.items():
            sync_clock[k] = max(sync_clock.get(k, 0), v)

    def absorb(self, sync_clock: dict[str, int]) -> None:
        """Acquire side: join the sync object's clock into the current
        vthread's (lock acquire, event wait return)."""
        clock = self._clock_of(self._vthread())
        for k, v in sync_clock.items():
            clock[k] = max(clock.get(k, 0), v)

    # -- verdict ---------------------------------------------------------------

    def assert_race_free(self) -> None:
        if self.races:
            raise RaceError(self.races)


# -- traced synchronization primitives ----------------------------------------


class TracedLock:
    """``asyncio.Lock`` with happens-before edges: everything before a
    release happens-before everything after the next acquire."""

    def __init__(self, tracker: RaceTracker):
        self._tracker = tracker
        self._inner = asyncio.Lock()
        self._clock: dict[str, int] = {}

    async def __aenter__(self):
        await self._inner.acquire()
        self._tracker.absorb(self._clock)
        return self

    async def __aexit__(self, *exc):
        self._tracker.publish(self._clock)
        self._inner.release()


class TracedEvent:
    """``asyncio.Event`` with a set→wait happens-before edge."""

    def __init__(self, tracker: RaceTracker):
        self._tracker = tracker
        self._inner = asyncio.Event()
        self._clock: dict[str, int] = {}

    def set(self) -> None:
        self._tracker.publish(self._clock)
        self._inner.set()

    def is_set(self) -> bool:
        return self._inner.is_set()

    async def wait(self) -> None:
        await self._inner.wait()
        self._tracker.absorb(self._clock)


# -- traced task manager -------------------------------------------------------


class TracedTaskManager:
    """Wraps a ``TaskManagerBase``-shaped manager for exploration.

    Records each access (``task:<id>`` reads for probes, writes for
    transitions) for the vector-clock tracker when one is given.

    ``hop`` chooses which deployment the fixture models:

    - ``hop=False`` (default) — **in-process store** (``LocalTaskManager``
      over ``InMemoryTaskStore``): store calls complete without
      suspending, exactly like production single-host. A probe
      immediately followed by its write is atomic; the interleaving
      windows are the code under test's own awaits (sleeps, backend
      POSTs, result-store hops) — the windows whose clobbers PRs 3-5
      actually shipped.
    - ``hop=True`` — **remote store** (``HttpTaskManager``): one
      ``yield_point()`` before every operation, the suspension the HTTP
      hop makes unavoidable. Under this model even probe-then-write has
      a one-suspension residual window — the accepted platform contract
      (writers that must win that window use the store's conditional
      verbs, ``update_status_if``/``requeue_if``; docs/concurrency.md).
      ``tests/test_race_regressions.py`` keeps that paragraph honest by
      demonstrating the residual window IS reachable under ``hop=True``.
    """

    def __init__(self, inner, tracker: RaceTracker | None = None,
                 hop: bool = False):
        self.inner = inner
        self.tracker = tracker
        self.hop = hop

    async def _pre(self, task_id: str, kind: str) -> None:
        if self.hop:
            await yield_point()
        if self.tracker is not None:
            self.tracker.record(f"task:{task_id}", kind)

    async def is_terminal(self, task_id: str) -> bool:
        await self._pre(task_id, "read")
        return await self.inner.is_terminal(task_id)

    async def get_task_status(self, task_id: str):
        await self._pre(task_id, "read")
        return await self.inner.get_task_status(task_id)

    async def update_task_status(self, task_id: str, status: str,
                                 backend_status: str | None = None):
        await self._pre(task_id, "write")
        return await self.inner.update_task_status(
            task_id, status, backend_status=backend_status)

    async def complete_task(self, task_id: str, status: str = "completed"):
        await self._pre(task_id, "write")
        return await self.inner.complete_task(task_id, status)

    async def fail_task(self, task_id: str, status: str = "failed"):
        await self._pre(task_id, "write")
        return await self.inner.fail_task(task_id, status)

    async def add_task(self, endpoint, body, task_id=None, publish=False):
        await self._pre(task_id or "", "write")
        return await self.inner.add_task(endpoint, body, task_id=task_id,
                                         publish=publish)

    async def add_pipeline_task(self, task_id, next_endpoint, body=b""):
        await self._pre(task_id, "write")
        return await self.inner.add_pipeline_task(task_id, next_endpoint,
                                                  body)

    def __getattr__(self, name):
        return getattr(self.inner, name)
