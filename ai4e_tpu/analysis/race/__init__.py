"""ai4e-race — deterministic interleaving exploration for the async task path.

The static rules (AIL007-AIL009) catch the check-then-act-across-await
*shape*; this package catches the *behavior*: it runs real platform
coroutines under a deterministic cooperative scheduler that controls task
ordering at every yield point, explores seeded-random plus
bounded-systematic interleavings, and tracks happens-before over
instrumented shared-state accesses with vector clocks — so the races the
PR 3/PR 4 chaos runs only hit by luck become reproducible unit tests
(``docs/concurrency.md`` has the operator view).

Three layers:

- ``scheduler``  — ``VirtualLoop``: a minimal virtual-clock event loop
  whose ready-queue pops are chosen by a ``Schedule`` (seeded random, or
  a forced-prefix replay for systematic search). Timers advance virtual
  time, so explored code sleeps for free and every run is
  byte-deterministic;
- ``explore``    — ``explore_interleavings(make_coros, schedules=N,
  seed=...)``: the pytest helper. Fresh state per schedule, systematic
  prefixes first, seeded random for the rest of the budget; same seed →
  same schedules → same verdict;
- ``hb``         — ``RaceTracker``: vector-clock happens-before over
  accesses recorded by the instrumentation wrappers (``TracedTaskManager``,
  ``TracedLock``, ``TracedEvent``, ``yield_point``), reporting racy access
  pairs with both stack traces.

Stdlib-only (like the rest of ``ai4e_tpu.analysis``): the CI ``race-smoke``
job runs without the JAX toolchain.
"""

from .explore import ExplorationReport, RunResult, explore_interleavings
from .hb import (RaceError, RaceTracker, TracedEvent, TracedLock,
                 TracedTaskManager, yield_point)
from .scheduler import (DeadlockError, PrefixSchedule, RandomSchedule,
                        ScheduleBudgetExceeded, VirtualLoop, run_schedule)

__all__ = [
    "DeadlockError",
    "ExplorationReport",
    "PrefixSchedule",
    "RaceError",
    "RaceTracker",
    "RandomSchedule",
    "RunResult",
    "ScheduleBudgetExceeded",
    "TracedEvent",
    "TracedLock",
    "TracedTaskManager",
    "VirtualLoop",
    "explore_interleavings",
    "run_schedule",
    "yield_point",
]
