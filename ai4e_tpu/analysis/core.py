"""Rule framework: findings, suppression, baseline, the analyzer driver.

Design notes (docs/analysis.md has the operator view):

- **Rules are AST visitors.** A per-module rule subclasses ``Rule`` and
  yields ``Finding``s from ``check_module``; a whole-project rule (e.g.
  AIL006 config-drift, which correlates code against ``docs/``) subclasses
  ``ProjectRule`` and runs once after every module is parsed.
- **Suppression is per line.** ``# ai4e: noqa[AIL001]`` (comma-list
  allowed) on the line a finding is reported at suppresses it. There is
  deliberately no file- or rule-wide off switch — a rule that needs one is
  a rule that should not have shipped.
- **The baseline grandfathers, it does not bless.** Baselined findings are
  matched by a line-number-free fingerprint (rule | path | enclosing
  symbol | normalized source line) so refactors that merely move code
  don't resurrect them, and every entry must carry a human-written
  justification — an empty one fails the run louder than the finding
  itself would have.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass, field, replace

# Matched only inside COMMENT tokens (tokenize), so the leading "#" is
# implicit — the marker can share a comment with other annotations
# ("# noqa: BLE001; ai4e: noqa[AILxxx] — reason"; the placeholder id
# keeps this example itself out of AIL019's unused-suppression sweep).
_NOQA_RE = re.compile(r"ai4e:\s*noqa\[([A-Za-z0-9_,\s]+)\]")

# Rule id for files the analyzer itself cannot parse: a syntax error means
# every other invariant is unverifiable, which is itself a finding.
PARSE_ERROR_RULE = "AIL000"

# Rule id for suppressions that suppress nothing (see Analyzer.run). The
# logic lives in the driver — it needs the full raw-finding set — but the
# id is registered as a normal catalog rule so --select/--ignore and the
# docs treat it uniformly.
_UNUSED_SUPPRESSION_RULE = "AIL019"


@dataclass(frozen=True)
class Finding:
    rule: str           # stable rule id, e.g. "AIL001"
    path: str           # repo-relative posix path
    line: int           # 1-based
    col: int            # 0-based
    message: str
    symbol: str = ""    # enclosing qualname ("Class.method"), "" at module level
    snippet: str = ""   # stripped source of the flagged line
    # k-th identical (rule, path, symbol, snippet) occurrence in source
    # order, assigned by Analyzer.run. Part of the fingerprint: without
    # it, one baseline entry would silently grandfather every NEW
    # byte-identical finding added to the same symbol later. Removing an
    # earlier twin shifts later ordinals — conservative by design: the
    # survivor resurfaces for re-justification rather than hiding.
    ordinal: int = 0
    # Rule-chosen identity override. The default fingerprint is keyed on
    # (path, symbol, snippet) — right for per-module rules, wrong for
    # wire-surface rules whose finding is about a CONTRACT, not a line:
    # moving a route registration between files must not churn the
    # baseline (the contract didn't change). Wire rules set this to the
    # contract identity ("AIL016|dead-route|GET /healthz").
    fingerprint_key: str = ""

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity for baseline matching: stable across
        pure moves/reformats of surrounding code, invalidated when the
        flagged line itself (or its enclosing symbol) changes. Rules may
        override the identity with ``fingerprint_key`` (wire contracts)."""
        if self.fingerprint_key:
            raw = f"{self.fingerprint_key}|{self.ordinal}"
        else:
            norm = " ".join(self.snippet.split())
            raw = f"{self.rule}|{self.path}|{self.symbol}|{norm}|{self.ordinal}"
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message, "symbol": self.symbol,
            "snippet": self.snippet, "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule}{sym} {self.message}"


@dataclass
class ModuleContext:
    """Everything a per-module rule sees."""
    path: str                 # repo-relative posix path
    abspath: str
    tree: ast.Module
    source: str
    lines: list[str] = field(default_factory=list)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str,
                symbol: str = "") -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message, symbol=symbol,
                       snippet=self.snippet(line))


@dataclass
class ProjectContext:
    """Everything a whole-project rule sees: every parsed module plus the
    repo root (for correlating against non-Python surfaces like docs/)."""
    root: str
    modules: list[ModuleContext]


class Rule:
    """Per-module rule. Subclasses set the class attributes and implement
    ``check_module``."""

    rule_id: str = ""
    name: str = ""
    description: str = ""
    # Catalog grouping for --list-rules: "invariants" (intra-process,
    # AIL001–AIL015), "wire" (cross-process contracts), "hygiene"
    # (the analyzer checking its own annotations).
    family: str = "invariants"

    def check_module(self, ctx: ModuleContext):  # pragma: no cover - interface
        raise NotImplementedError


class ProjectRule(Rule):
    """Whole-project rule: runs once, after every module is parsed."""

    def check_module(self, ctx: ModuleContext):
        return ()

    def check_project(self, ctx: ProjectContext):  # pragma: no cover - interface
        raise NotImplementedError


# -- shared AST helpers (used by several rules) ------------------------------


def import_aliases(tree: ast.AST) -> dict[str, str]:
    """Map local names to the canonical dotted name they import, across the
    whole module (function-level imports included — the codebase uses lazy
    imports heavily for optional deps and cycle breaking).

    ``import time as t``           → {"t": "time"}
    ``from time import sleep``     → {"sleep": "time.sleep"}
    ``from urllib import request`` → {"request": "urllib.request"}
    """
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST, aliases: dict[str, str] | None = None
                ) -> str | None:
    """Resolve an attribute chain to a dotted name; the leftmost ``Name``
    goes through the module's import aliases when given. Returns None for
    chains rooted at calls/subscripts (dynamic — unresolvable)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = node.id
    if aliases and root in aliases:
        root = aliases[root]
    parts.append(root)
    return ".".join(reversed(parts))


def enclosing_symbol(stack: list[ast.AST]) -> str:
    names = [getattr(n, "name", "") for n in stack
             if isinstance(n, (ast.ClassDef, ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    return ".".join(n for n in names if n)


# -- await-aware flow (used by the AIL007-AIL009 concurrency rules) ----------


#: Statement-level suspension constructs. ``ast.Await`` is the third kind,
#: collected expression-side.
_SUSPENDING_STMTS = (ast.AsyncFor, ast.AsyncWith)
_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _pos(node: ast.AST) -> tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


class AwaitFlow:
    """Lightweight CFG-over-suspension-points for ONE async function.

    The question the concurrency rules need answered is narrow: *between
    this guard evaluation and that write, can the coroutine suspend?* —
    because every suspension hands the event loop to arbitrary other tasks,
    invalidating anything the guard read. Rather than a full CFG this
    models exactly that:

    - **suspension points** are ``await`` expressions, ``async for`` loops
      and ``async with`` entries/exits, collected in source order; nested
      ``def``/``async def``/``lambda`` bodies are excluded (they suspend
      their own callers, not this frame);
    - ``suspensions_between(a, b)`` counts suspension points that can
      execute after ``a`` completes and before ``b`` starts on SOME path
      (exists-path semantics — a linter must flag the racy path even when
      a clean one exists). Approximations, all deliberate:

      * source position orders evaluation (true within a statement list;
        branch bodies are corrected for below);
      * a suspension inside one arm of an ``if`` is excluded when ``b``
        sits in the *other* arm (no path through both);
      * **back edges**: when ``b`` is inside a loop that ``a`` is NOT in,
        every suspension in that loop counts — iteration ``n+1`` reaches
        ``b`` after the iteration-``n`` suspensions, however they are
        ordered in source. When ``a`` and ``b`` share the loop the back
        edge re-executes ``a`` too (the guard is re-evaluated each
        iteration), so only the source-ordered window counts.

    ``dominates(g, w)`` answers the guard-placement half: an ``if``/
    ``while`` TEST is evaluated on every path through the statement, so a
    probe in a test guards everything after it; a probe inside one branch
    body guards only that branch's descendants.
    """

    def __init__(self, fn: ast.AsyncFunctionDef | ast.FunctionDef):
        self.fn = fn
        self._parent: dict[ast.AST, ast.AST] = {}
        self.suspensions: list[ast.AST] = []
        self._collect(fn, parent=None, top=True)

    def _collect(self, node: ast.AST, parent: ast.AST | None,
                 top: bool = False) -> None:
        if parent is not None:
            self._parent[node] = parent
        if not top and isinstance(node, _NESTED_SCOPES):
            return  # a nested scope's awaits suspend the nested frame
        if isinstance(node, ast.Await) or isinstance(node, _SUSPENDING_STMTS):
            self.suspensions.append(node)
        for child in ast.iter_child_nodes(node):
            self._collect(child, node)

    # -- structure queries --------------------------------------------------

    def _ancestors(self, node: ast.AST) -> list[ast.AST]:
        out = []
        while node in self._parent:
            node = self._parent[node]
            out.append(node)
        return out

    def in_subtree(self, node: ast.AST, root: ast.AST) -> bool:
        return node is root or root in self._ancestors(node)

    def _branch_of(self, node: ast.AST, stmt: ast.stmt) -> str | None:
        """Which field of ``stmt`` (an If/Try/loop) the ancestor path to
        ``node`` enters through: 'test', 'body', 'orelse', 'handlers',
        'finalbody', 'iter' — None when ``node`` is not inside ``stmt``."""
        chain = [node, *self._ancestors(node)]
        try:
            child_idx = chain.index(stmt) - 1
        except ValueError:
            return None
        if child_idx < 0:
            return None
        child = chain[child_idx]
        for field, value in ast.iter_fields(stmt):
            if value is child:
                return field
            if isinstance(value, list) and any(v is child for v in value):
                return field
        return None

    def lift_to_await(self, node: ast.AST) -> ast.AST:
        """The evaluation anchor of ``node``: its enclosing ``Await`` when
        it is directly awaited (``await probe()`` — the await IS the
        probe's suspension, not an intervening one), else ``node``."""
        parent = self._parent.get(node)
        if isinstance(parent, ast.Await):
            return parent
        return node

    def _enclosing_loops(self, node: ast.AST) -> list[ast.AST]:
        return [a for a in self._ancestors(node)
                if isinstance(a, (ast.For, ast.While, ast.AsyncFor))
                and self._branch_of(node, a) == "body"]

    def dominates(self, guard: ast.AST, write: ast.AST) -> bool:
        """Whether every path reaching ``write`` evaluated ``guard`` first
        (syntactic approximation). A guard in an ``if``/``while`` TEST
        dominates everything positioned after it; a guard inside a branch
        body/handler dominates only that branch's own descendants."""
        if _pos(guard) > _pos(write):
            return False
        for anc in self._ancestors(guard):
            if self.in_subtree(write, anc):
                return True  # reached the common ancestor: every step up
                # to here kept write inside guard's branch
            if isinstance(anc, (ast.If, ast.While)):
                if self._branch_of(guard, anc) == "test":
                    continue  # tests run on every path through the stmt
                return False  # guard in one arm, write outside the stmt
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.Try,
                                ast.With, ast.AsyncWith)):
                branch = self._branch_of(guard, anc)
                if branch in ("handlers", "orelse", "finalbody"):
                    return False  # exceptional/conditional arm only
                if isinstance(anc, (ast.For, ast.AsyncFor)):
                    return False  # loop body may run zero times
        return True

    def suspensions_between(self, a: ast.AST, b: ast.AST) -> list[ast.AST]:
        """Suspension points that can execute after ``a`` and before ``b``
        on some path (see class docstring for the approximation), excluding
        suspensions inside ``a``'s or ``b``'s own subtrees."""
        a_loops = set(map(id, self._enclosing_loops(a)))
        b_loops = self._enclosing_loops(b)
        back_edge_loops = [L for L in b_loops if id(L) not in a_loops]
        out = []
        for s in self.suspensions:
            if self.in_subtree(s, a) or self.in_subtree(s, b):
                continue
            if any(self.in_subtree(s, L) for L in back_edge_loops):
                out.append(s)  # iteration n+1 reaches b after s
                continue
            if not (_pos(a) < _pos(s) < _pos(b)):
                continue
            if self._branch_disjoint(s, b) or self._branch_disjoint(s, a):
                continue
            out.append(s)
        return out

    def _branch_disjoint(self, s: ast.AST, other: ast.AST) -> bool:
        """True when ``s`` and ``other`` sit in different arms of the same
        ``if`` — no single path executes both."""
        for anc in self._ancestors(s):
            if isinstance(anc, ast.If) and self.in_subtree(other, anc):
                sb = self._branch_of(s, anc)
                ob = self._branch_of(other, anc)
                if (sb in ("body", "orelse") and ob in ("body", "orelse")
                        and sb != ob):
                    return True
        return False


# -- parse cache -------------------------------------------------------------


#: (abspath) → (mtime_ns, size, tree, source). Parsing dominates analyzer
#: wall time (one full-repo run parses ~200 files); within one process —
#: the test suite, a watch loop, repeated Analyzer.run calls — a file
#: whose stat signature is unchanged reuses the parsed tree. Rules treat
#: trees as read-only (nothing in the framework mutates them), so sharing
#: across runs is safe. Bounded: blown away wholesale past _PARSE_CACHE_MAX
#: entries rather than LRU-tracked — the workload is "same repo, many
#: runs", where eviction precision buys nothing.
_PARSE_CACHE: dict[str, tuple[int, int, ast.Module, str]] = {}
_PARSE_CACHE_MAX = 4096


def parse_module(abspath: str, relpath: str) -> ModuleContext:
    """Parse ``abspath`` into a ModuleContext (fresh context, cached
    tree/source keyed on mtime+size). Raises OSError/SyntaxError/
    ValueError exactly like ``ast.parse`` — callers decide whether a
    parse failure is a finding (Analyzer: AIL000) or a skip."""
    abspath = os.path.abspath(abspath)
    st = os.stat(abspath)
    hit = _PARSE_CACHE.get(abspath)
    if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
        tree, source = hit[2], hit[3]
    else:
        with open(abspath, encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=abspath)
        if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            _PARSE_CACHE.clear()
        _PARSE_CACHE[abspath] = (st.st_mtime_ns, st.st_size, tree, source)
    return ModuleContext(path=relpath, abspath=abspath, tree=tree,
                         source=source, lines=source.splitlines())


# -- suppression -------------------------------------------------------------


def noqa_lines(source: str) -> dict[int, frozenset[str]]:
    """Line → suppressed rule ids, from ``# ai4e: noqa[AIL001,AIL005]``
    comments. Tokenize-based so strings containing the marker don't count."""
    out: dict[int, frozenset[str]] = {}
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _NOQA_RE.search(tok.string)
            if not m:
                continue
            rules = frozenset(r.strip().upper()
                              for r in m.group(1).split(",") if r.strip())
            if rules:
                out[tok.start[0]] = out.get(tok.start[0], frozenset()) | rules
    except tokenize.TokenError:
        pass
    return out


# -- baseline ----------------------------------------------------------------


class BaselineError(Exception):
    """The baseline file is unusable (unparseable, or an entry has no
    written justification) — a configuration error, exit 2, distinct from
    findings (exit 1)."""


class Baseline:
    """Checked-in grandfather list. Schema::

        {"version": 1,
         "findings": [{"rule": "AIL005", "path": "...", "symbol": "...",
                       "fingerprint": "...", "justification": "why"}]}
    """

    def __init__(self, entries: list[dict] | None = None, path: str = ""):
        self.path = path
        self.entries = entries or []
        self._by_fp = {e.get("fingerprint", ""): e for e in self.entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except FileNotFoundError:
            return cls([], path)
        except (OSError, ValueError) as exc:
            raise BaselineError(f"cannot read baseline {path}: {exc}") from exc
        entries = data.get("findings", [])
        for e in entries:
            if not str(e.get("justification", "")).strip():
                raise BaselineError(
                    f"baseline {path}: entry {e.get('fingerprint', '?')} "
                    f"({e.get('rule', '?')} in {e.get('path', '?')}) has no "
                    "written justification — baselining without a reason is "
                    "just hiding the finding")
        return cls(entries, path)

    def match(self, finding: Finding) -> dict | None:
        return self._by_fp.get(finding.fingerprint)

    def stale(self, findings: list[Finding]) -> list[dict]:
        """Entries whose finding no longer exists — candidates for removal."""
        live = {f.fingerprint for f in findings}
        return [e for e in self.entries
                if e.get("fingerprint", "") not in live]

    @staticmethod
    def write(path: str, findings: list[Finding]) -> None:
        """Seed a baseline from current findings. Justifications are left
        EMPTY on purpose: the very next run refuses the file until a human
        writes one per entry — grandfathering is a decision, not a default."""
        entries = [{"rule": f.rule, "path": f.path, "symbol": f.symbol,
                    "snippet": f.snippet, "fingerprint": f.fingerprint,
                    "justification": ""} for f in findings]
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"version": 1, "findings": entries}, fh, indent=2)
            fh.write("\n")


# -- analyzer ----------------------------------------------------------------


@dataclass
class AnalysisResult:
    findings: list[Finding]            # active (not suppressed, not baselined)
    baselined: list[Finding]
    suppressed: int
    stale_baseline: list[dict]
    files_scanned: int
    # --stats surface: where the run's wall time went. ``rule_seconds``
    # is keyed by rule id, source-order preserved by dict insertion.
    parse_seconds: float = 0.0
    total_seconds: float = 0.0
    rule_seconds: dict[str, float] = field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.findings


def _iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


class Analyzer:
    def __init__(self, rules: list[Rule], root: str | None = None,
                 baseline: Baseline | None = None):
        self.rules = rules
        self.root = os.path.abspath(root) if root else os.getcwd()
        self.baseline = baseline or Baseline()

    def _relpath(self, path: str) -> str:
        rel = os.path.relpath(os.path.abspath(path), self.root)
        return rel.replace(os.sep, "/")

    def run(self, paths: list[str]) -> AnalysisResult:
        t_run = time.perf_counter()
        files = _iter_py_files(paths)
        modules: list[ModuleContext] = []
        raw: list[Finding] = []
        suppressions: dict[str, dict[int, frozenset[str]]] = {}
        by_rel: dict[str, ModuleContext] = {}
        parse_seconds = 0.0
        rule_seconds: dict[str, float] = {
            r.rule_id: 0.0 for r in self.rules}
        for path in files:
            rel = self._relpath(path)
            t0 = time.perf_counter()
            try:
                ctx = parse_module(path, rel)
            except (OSError, SyntaxError, ValueError) as exc:
                parse_seconds += time.perf_counter() - t0
                line = getattr(exc, "lineno", 1) or 1
                raw.append(Finding(
                    rule=PARSE_ERROR_RULE, path=rel, line=line, col=0,
                    message=f"cannot parse: {exc}", snippet=""))
                continue
            parse_seconds += time.perf_counter() - t0
            modules.append(ctx)
            by_rel[rel] = ctx
            suppressions[rel] = noqa_lines(ctx.source)
            for rule in self.rules:
                if isinstance(rule, ProjectRule):
                    continue
                t0 = time.perf_counter()
                raw.extend(rule.check_module(ctx))
                rule_seconds[rule.rule_id] += time.perf_counter() - t0
        project_ctx = ProjectContext(root=self.root, modules=modules)
        for rule in self.rules:
            if isinstance(rule, ProjectRule):
                t0 = time.perf_counter()
                raw.extend(rule.check_project(project_ctx))
                rule_seconds[rule.rule_id] += time.perf_counter() - t0

        # AIL019 — unused suppressions. A ``# ai4e: noqa[AILxxx]`` whose
        # rule did not fire on that line is dead weight at best and a
        # masked regression at worst (the bug was fixed, the blindfold
        # stayed on). Only rules ACTIVE in this run are judged: under
        # ``--select`` a suppression for an unselected rule is unproven,
        # not unused. Suppressing AIL019 itself on the line (noqa[AIL005,
        # AIL019]) works through the normal pipeline below.
        active_ids = {r.rule_id for r in self.rules}
        if _UNUSED_SUPPRESSION_RULE in active_ids:
            t0 = time.perf_counter()
            fired = {(f.path, f.line, f.rule) for f in raw}
            for rel in sorted(suppressions):
                for line, ids in sorted(suppressions[rel].items()):
                    for rid in sorted(ids):
                        if (rid == _UNUSED_SUPPRESSION_RULE
                                or rid not in active_ids
                                or (rel, line, rid) in fired):
                            continue
                        mod = by_rel.get(rel)
                        raw.append(Finding(
                            rule=_UNUSED_SUPPRESSION_RULE, path=rel,
                            line=line, col=0,
                            message=(f"suppression `ai4e: noqa[{rid}]` has "
                                     f"no effect — {rid} does not fire on "
                                     "this line; drop it (a stale noqa "
                                     "masks the next real finding)"),
                            snippet=mod.snippet(line) if mod else ""))
            rule_seconds[_UNUSED_SUPPRESSION_RULE] += time.perf_counter() - t0

        # Assign occurrence ordinals in source order so byte-identical
        # findings in the same symbol get distinct fingerprints.
        raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        seen_keys: dict[tuple, int] = {}
        stamped: list[Finding] = []
        for f in raw:
            key = (f.rule, f.path, f.symbol, " ".join(f.snippet.split()))
            k = seen_keys.get(key, 0)
            seen_keys[key] = k + 1
            stamped.append(replace(f, ordinal=k) if k else f)
        raw = stamped

        active: list[Finding] = []
        baselined: list[Finding] = []
        suppressed = 0
        for f in raw:
            if f.rule in suppressions.get(f.path, {}).get(f.line, frozenset()):
                suppressed += 1
                continue
            if self.baseline.match(f) is not None:
                baselined.append(f)
                continue
            active.append(f)
        active.sort(key=lambda f: (f.path, f.line, f.rule))
        matched = baselined + active
        return AnalysisResult(
            findings=active, baselined=baselined, suppressed=suppressed,
            stale_baseline=self.baseline.stale(matched),
            files_scanned=len(files),
            parse_seconds=parse_seconds,
            total_seconds=time.perf_counter() - t_run,
            rule_seconds=rule_seconds)
