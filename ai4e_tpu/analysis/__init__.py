"""ai4e-lint — AST-based platform-invariant analyzer (docs/analysis.md).

The platform is ~18k LoC of heavily concurrent asyncio serving code, and
the same hand-findable bug classes kept reappearing in review: dispatch
metrics silently landing in ``DEFAULT_REGISTRY`` instead of the assembly
registry, terminal-task-status clobbers causing double completions (the
PR 3 chaos harness caught a live one), blocking calls stalling the event
loop. Each rule here encodes one of those past bugs as a machine-checked
invariant, so later perf/refactor PRs can move fast without regressing
them (the "systematic, not artisanal" stance of PAPERS.md's adaptive-
orchestration paper, applied to correctness invariants).

Usage::

    python -m ai4e_tpu.analysis ai4e_tpu/          # exit 1 on findings
    python -m ai4e_tpu.analysis --json ai4e_tpu/   # machine-readable
    python -m ai4e_tpu.analysis --list-rules

Suppression: ``# ai4e: noqa[AIL001]`` on the flagged line (comma-list for
several rules). Grandfathering: a checked-in baseline file where every
entry carries a written justification (``--baseline``/``--write-baseline``).

Stdlib-only by design: the CI gate must not need the JAX toolchain.

The dynamic counterpart lives in ``ai4e_tpu.analysis.race`` (also
stdlib-only): a deterministic interleaving explorer that runs the async
task path's critical sections under schedule control and catches the
races AIL007-AIL009 check the shape of — ``docs/concurrency.md``.
"""

from .core import (AnalysisResult, Analyzer, Baseline, BaselineError,
                   Finding, ModuleContext, ProjectContext, ProjectRule, Rule)
from .rules import ALL_RULES

__all__ = [
    "ALL_RULES",
    "AnalysisResult",
    "Analyzer",
    "Baseline",
    "BaselineError",
    "Finding",
    "ModuleContext",
    "ProjectContext",
    "ProjectRule",
    "Rule",
]
