"""Shared per-backend health model — breakers + health-aware routing.

One ``BackendHealth`` instance per platform assembly, shared by the
gateway sync proxy and every dispatcher, so a backend that is melting
under the dispatcher's deliveries is ALSO ejected from the sync proxy's
picks (and vice versa) — the two surfaces see one truth.

Routing policy (``pick``):

- every backend whose breaker admits traffic keeps its configured weight;
- an OPEN backend is **ejected**: its weight implicitly redistributes
  across the remaining healthy set (``random.choices`` over the
  survivors — no renormalization pass needed, relative weights are the
  contract ``utils/backends.py`` already defines);
- a half-open backend competes at its normal weight but the breaker
  bounds its in-flight probes, so recovery traffic is a trickle, not a
  stampede;
- **all open** (fully-dark set): route to the least-recently-failed
  backend as a forced probe — a dark set must keep probing its way back
  to life, because with every breaker open there is nobody else to try.

Exported metrics (``ai4e_resilience_*``, docs/METRICS.md): breaker state
per backend, open/close transitions, ejections, retries, failovers, and
probe outcomes.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from urllib.parse import urlparse

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..utils.backends import Weighted, pick_backend
from .breaker import STATE_CODES, CircuitBreaker
from .retry import RetryBudget


@dataclass
class ResiliencePolicy:
    """The assembly-level knob set (``PlatformConfig`` mirrors these —
    ``resilience_*`` fields / ``AI4E_PLATFORM_*`` env vars)."""

    failure_threshold: int = 5       # consecutive failures that trip a breaker
    window: int = 16                 # rolling outcome window (error-rate trip)
    error_rate: float = 0.5          # window failure fraction that trips
    recovery_seconds: float = 30.0   # open → half-open cooldown
    half_open_probes: int = 1        # concurrent probes while half-open
    max_attempts: int = 3            # delivery attempts per POST (1 + retries)
    retry_base_s: float = 0.05       # first in-attempt retry delay (jittered)
    retry_cap_s: float = 1.0         # in-attempt retry delay ceiling
    retry_budget_ratio: float = 0.2  # retries per ordinary request, steady state
    drain_eject_ttl_s: float = 30.0  # placement eject per X-Draining mark (rollout/)


class BackendHealth:
    """Breaker registry + health-aware weighted pick (module docstring)."""

    def __init__(self, policy: ResiliencePolicy | None = None,
                 metrics: MetricsRegistry | None = None,
                 clock=time.monotonic, rng: random.Random | None = None):
        self.policy = policy or ResiliencePolicy()
        self.metrics = metrics or DEFAULT_REGISTRY
        self._clock = clock
        self._rng = rng
        self._breakers: dict[str, CircuitBreaker] = {}
        self._state_gauge = self.metrics.gauge(
            "ai4e_resilience_breaker_state",
            "Breaker state per backend: 0 closed, 1 half-open, 2 open")
        self._transitions = self.metrics.counter(
            "ai4e_resilience_transitions_total",
            "Breaker state transitions by backend and new state")
        self._ejections = self.metrics.counter(
            "ai4e_resilience_ejections_total",
            "Weighted picks that routed around an open backend")
        self._retries = self.metrics.counter(
            "ai4e_resilience_retries_total",
            "In-attempt retries by component")
        self._failovers = self.metrics.counter(
            "ai4e_resilience_failovers_total",
            "Retries that switched to a different backend, by component")
        self._probes = self.metrics.counter(
            "ai4e_resilience_probe_total",
            "Half-open/forced probe outcomes by backend")
        # Drain ejections (rollout/, docs/deployment.md#drain): a backend
        # that answered 503 + X-Draining told us it is LEAVING — eject it
        # from placement for a TTL. Deliberately NOT a breaker state:
        # draining is orderly, a breaker trip would smear a planned
        # upgrade as a failure in every dashboard keyed on breaker
        # transitions. uri -> monotonic deadline.
        self._draining: dict[str, float] = {}
        self._drain_ejections = self.metrics.counter(
            "ai4e_rollout_drain_ejections_total",
            "Weighted picks that routed around a draining backend")
        # Canary split policy (rollout/canary.py CanaryWeights), attached
        # by the assembly when a rollout is live; None = no reweighting.
        self._canary = None

    # -- registry -----------------------------------------------------------

    @staticmethod
    def _label(uri: str) -> str:
        """Metrics label for a backend URI — the host, matching the
        ``backend`` dimension ``ai4e_dispatch_total`` already exports."""
        return urlparse(uri).netloc or uri

    def breaker_for(self, uri: str) -> CircuitBreaker:
        br = self._breakers.get(uri)
        if br is None:
            p = self.policy
            br = self._breakers[uri] = CircuitBreaker(
                failure_threshold=p.failure_threshold, window=p.window,
                error_rate=p.error_rate,
                recovery_seconds=p.recovery_seconds,
                half_open_probes=p.half_open_probes, clock=self._clock)
            self._state_gauge.set(0, backend=self._label(uri))
        return br

    def state(self, uri: str) -> str:
        return self.breaker_for(uri).state

    def new_budget(self) -> RetryBudget:
        """A retry budget at this policy's ratio — one per retrying
        component (each dispatcher queue, the sync proxy)."""
        return RetryBudget(ratio=self.policy.retry_budget_ratio)

    # -- drain eject (rollout/) ---------------------------------------------

    def mark_draining(self, uri: str, ttl_s: float | None = None) -> None:
        """Eject ``uri`` from placement for ``ttl_s`` (default: the
        policy's ``drain_eject_ttl_s`` — AI4E_ROLLOUT_DRAIN_EJECT_TTL_S)
        — called when a response carried ``X-Draining`` (the worker's
        drain refusal) or by the rollout driver before it drains a
        worker. TTL-bounded so a worker that comes back (rollback
        resume, restart at the new generation) re-enters placement
        without an explicit clear."""
        if ttl_s is None:
            ttl_s = self.policy.drain_eject_ttl_s
        self._draining[uri] = self._clock() + max(0.0, ttl_s)

    def clear_draining(self, uri: str) -> None:
        self._draining.pop(uri, None)

    def reset(self, uri: str) -> None:
        """Forget a backend's breaker history and drain mark — the
        rollout driver's post-restart hook: a deliberately replaced
        process re-enters placement with a clean slate instead of
        inheriting the connect failures its own restart window minted
        (which would read as an open canary breaker and roll back a
        healthy upgrade)."""
        self._draining.pop(uri, None)
        if self._breakers.pop(uri, None) is not None:
            self._state_gauge.set(0, backend=self._label(uri))

    def is_draining(self, uri: str) -> bool:
        deadline = self._draining.get(uri)
        if deadline is None:
            return False
        if self._clock() >= deadline:
            del self._draining[uri]
            return False
        return True

    # -- canary split (rollout/) --------------------------------------------

    def attach_canary(self, canary) -> None:
        """Attach a ``CanaryWeights`` policy: both placement surfaces
        (``pick`` here, the orchestrator's in-tier choice) then split
        in-tier traffic between generations."""
        self._canary = canary

    @property
    def canary(self):
        return self._canary

    # -- routing ------------------------------------------------------------

    def pick(self, backends: Weighted, rng: random.Random | None = None,
             exclude=()) -> str:
        """Health-aware weighted pick. ``exclude``: backends already tried
        in THIS delivery attempt chain (failover must reach a *different*
        backend when one exists); ignored when it would empty the set."""
        now = self._clock()
        pool = [(u, w) for u, w in backends if u not in exclude and w > 0]
        if not pool:
            pool = [(u, w) for u, w in backends if w > 0]
        # Drain eject (rollout/): a draining backend told us it is
        # leaving — route around it while anyone else remains. When the
        # WHOLE pool is draining (single-replica shard mid-upgrade) keep
        # the pool: a drain refusal redelivers, a no-backend error loses.
        undrained = [(u, w) for u, w in pool if not self.is_draining(u)]
        if undrained and len(undrained) < len(pool):
            for uri, _ in pool:
                if self.is_draining(uri):
                    self._drain_ejections.inc(backend=self._label(uri))
            pool = undrained
        # Canary split (rollout/canary.py): rescale so the canary
        # generation holds its configured share of the pool's weight.
        if self._canary is not None:
            pool = self._canary.apply(pool)
        candidates = []
        ejected = []
        for uri, weight in pool:
            if self.breaker_for(uri).available(now):
                candidates.append((uri, weight))
            else:
                ejected.append(uri)
        if candidates and all(w <= 0 for _, w in candidates):
            # The canary rescale can zero a subset (share 0 or 1); when
            # breaker ejections leave ONLY that subset available, serve
            # it evenly rather than crash the pick — a zero-weight
            # survivor beats no backend at all.
            candidates = [(u, 1.0) for u, _ in candidates]
        if candidates:
            # Ejections counted only when somebody healthy absorbed the
            # traffic — an all-dark set's forced probe below routes INTO
            # the open backend, which is not an ejection.
            for uri in ejected:
                self._ejections.inc(backend=self._label(uri))
            chosen = pick_backend(candidates, rng or self._rng)
        else:
            # Fully dark: forced probe of the least-recently-failed
            # backend — the one most likely to have had time to recover.
            chosen = min((u for u, _ in pool),
                         key=lambda u: self.breaker_for(u).last_failure_at)
        self.commit_pick(chosen, now)
        return chosen

    def commit_pick(self, uri: str, now: float | None = None) -> None:
        """Account a routing decision made on this health model's state —
        by ``pick`` above or by an out-of-band placement policy (the
        orchestration scheduler): a non-closed breaker books the probe
        slot, so recovery traffic is bounded identically no matter who
        chose the backend."""
        br = self.breaker_for(uri)
        if br.state != "closed":
            br.begin_probe(self._clock() if now is None else now)
            self._set_state(uri, br)

    # -- outcome recording --------------------------------------------------

    def record_success(self, uri: str) -> None:
        br = self.breaker_for(uri)
        probing = br.state != "closed"
        br.record_success()
        if probing and br.state == "closed":
            # Actually recovered (half-open probe). A stale success
            # against a still-OPEN breaker is ignored by the state machine
            # and must not count a probe/transition either.
            self._probes.inc(backend=self._label(uri), outcome="success")
            self._transitions.inc(backend=self._label(uri), state="closed")
        self._set_state(uri, br)

    def record_failure(self, uri: str) -> bool:
        """Record a failure; True when the breaker opened on this call."""
        br = self.breaker_for(uri)
        probing = br.state != "closed"
        opened = br.record_failure(self._clock())
        if probing:
            self._probes.inc(backend=self._label(uri), outcome="failure")
        if opened:
            self._transitions.inc(backend=self._label(uri), state="open")
        self._set_state(uri, br)
        return opened

    def observe_status(self, uri: str, status: int) -> bool:
        """Classify an HTTP response for the breaker: 5xx (other than 503
        backpressure) is a failure, 429/503 is a *saturation* signal — the
        backend answered, it is alive, and ejecting it would shift load
        onto peers that are probably saturating too (admission control
        owns that signal) — and everything else is a success. Returns
        True when the breaker opened."""
        if status in (429, 503):
            # Neutral for open/close decisions, but it RESOLVES a probe:
            # without the release, one 503'd half-open probe would pin the
            # probe slot and eject the backend permanently.
            self.breaker_for(uri).record_neutral()
            return False
        if status >= 500:
            return self.record_failure(uri)
        self.record_success(uri)
        return False

    def note_retry(self, component: str) -> None:
        self._retries.inc(component=component)

    def note_failover(self, component: str) -> None:
        self._failovers.inc(component=component)

    def _set_state(self, uri: str, br: CircuitBreaker) -> None:
        self._state_gauge.set(STATE_CODES[br.state],
                              backend=self._label(uri))
