"""Resilient routing under failure (``docs/resilience.md``).

Opt-in via ``PlatformConfig(resilience=True)`` /
``AI4E_PLATFORM_RESILIENCE=1``. Three parts:

- ``breaker`` — per-backend circuit breaker (closed → open on
  consecutive-failure/error-rate threshold → half-open probe → closed);
- ``health``  — the ``BackendHealth`` registry the gateway sync proxy and
  every dispatcher share: health-aware weighted picks that eject open
  backends (redistributing their weight), last-resort least-recently-
  failed probing when the whole set is dark, and the
  ``ai4e_resilience_*`` metric family;
- ``retry``   — Finagle-style retry budgets and half-jittered exponential
  backoff, so retries can neither storm a browning-out backend nor wake
  in synchronized herds.

The deterministic fault-injection harness that proves all of this lives
in ``ai4e_tpu/chaos/``.
"""

from .breaker import CLOSED, HALF_OPEN, OPEN, STATE_CODES, CircuitBreaker
from .health import BackendHealth, ResiliencePolicy
from .retry import RetryBudget, backoff_s

__all__ = [
    "BackendHealth", "CircuitBreaker", "ResiliencePolicy", "RetryBudget",
    "backoff_s", "CLOSED", "HALF_OPEN", "OPEN", "STATE_CODES",
]
