"""Retry budgets and jittered backoff — bounded, storm-proof retries.

Two failure amplifiers hide in naive retry loops, and this module exists
to kill both:

- **retry storms**: when a backend browns out, every caller retrying N
  times multiplies the offered load by N exactly when capacity halved.
  ``RetryBudget`` is the Finagle-style token bucket: ordinary requests
  deposit ``ratio`` tokens, each retry spends one — so steady-state
  retries can never exceed ~``ratio`` of real traffic, with a small
  fixed reserve so cold starts and singleton failures still get their
  retry.
- **synchronized herds**: unjittered exponential backoff turns one
  outage into evenly-spaced waves of simultaneous retries.
  ``backoff_s`` spreads each delay uniformly over [d/2, d] (half
  jitter), so no two callers wake in lockstep.
"""

from __future__ import annotations

import random


def backoff_s(attempt: int, base: float, cap: float,
              rng: random.Random | None = None) -> float:
    """Jittered exponential delay for retry ``attempt`` (1-based): the
    deterministic schedule is ``base * 2**(attempt-1)`` capped at ``cap``;
    the returned delay is uniform in [schedule/2, schedule]. ``rng`` is
    injectable so tests and the chaos harness stay seeded."""
    if base <= 0 or cap <= 0:
        return 0.0
    # Exponent clamped: attempt counts are unbounded upstream (the broker
    # allows 1440 redeliveries), and 2**1019 overflows float — which would
    # turn the sleep into an exception, i.e. NO backoff at all, exactly
    # when a long-dark backend needs it most. 2**63·base dwarfs any cap.
    delay = min(cap, base * (2 ** min(63, max(0, attempt - 1))))
    return delay * (0.5 + 0.5 * (rng or random).random())


class RetryBudget:
    """Token-bucket retry budget (see module docstring). Event-loop-only
    state, like the breaker — each retrying component (one per dispatcher
    queue, one for the gateway sync proxy) owns its own budget so a
    melting queue cannot spend another queue's retries."""

    def __init__(self, ratio: float = 0.2, reserve: float = 10.0,
                 cap: float = 100.0):
        self.ratio = max(0.0, ratio)
        self.cap = max(reserve, cap)
        self._tokens = min(float(reserve), self.cap)

    @property
    def tokens(self) -> float:
        return self._tokens

    def on_request(self) -> None:
        """One ordinary (non-retry) request happened: deposit."""
        self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_retry(self) -> bool:
        """Spend one retry if the budget allows."""
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False
