"""Per-backend circuit breaker — the unit of the platform's health model.

The reference has no per-backend failure state at all: a crashed or
flapping pod keeps receiving its full weighted share of traffic until an
operator rolls the deployment (``BackendQueueProcessor.cs:54-64`` only
knows "retry the message in 60 s"). Here every backend URI a dispatcher
or the gateway sync proxy can target carries one breaker:

- **closed** — healthy; failures are counted (consecutive run + a rolling
  outcome window) but traffic flows normally;
- **open** — tripped on ``failure_threshold`` consecutive failures OR a
  window error rate at/above ``error_rate``; the backend is ejected from
  weighted picks (``health.BackendHealth.pick``) until
  ``recovery_seconds`` elapse;
- **half-open** — the cooldown elapsed; a bounded number of probe
  requests may flow. One success closes the breaker; one failure re-opens
  it (and restarts the cooldown from the failure, not from the original
  trip — a backend that fails its probe is as dead as it ever was).

The clock is injectable so tests (and the chaos harness) drive state
transitions deterministically — no sleeps, no wall-clock flake.
"""

from __future__ import annotations

import time
from collections import deque

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Gauge encoding for ai4e_resilience_breaker_state (docs/METRICS.md).
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Single backend's failure state machine. Event-loop-only (no lock):
    every caller — dispatcher delivery loops, the gateway sync proxy —
    records outcomes from the platform's event loop."""

    def __init__(self, failure_threshold: int = 5, window: int = 16,
                 error_rate: float = 0.5, recovery_seconds: float = 30.0,
                 half_open_probes: int = 1, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if not (0.0 < error_rate <= 1.0):
            raise ValueError("error_rate must be in (0, 1]")
        self.failure_threshold = failure_threshold
        self.error_rate = error_rate
        self.recovery_seconds = recovery_seconds
        self.half_open_probes = max(1, half_open_probes)
        self._clock = clock
        self.state = CLOSED
        self._consecutive = 0
        # Rolling outcome window (True = success): catches the flapping
        # backend the consecutive counter misses — one that interleaves
        # enough successes to keep resetting the run but still fails half
        # its traffic.
        self._window: deque[bool] = deque(maxlen=max(1, window))
        self._opened_at = 0.0
        self._probes_inflight = 0
        self._probe_started_at = 0.0
        self.last_failure_at = 0.0
        # Monotone counters for observers (health.py mirrors them into the
        # metrics registry with the backend label).
        self.opened_count = 0

    # -- routing queries ----------------------------------------------------

    def available(self, now: float | None = None) -> bool:
        """May this backend receive ordinary (non-forced) traffic now?
        Pure query — no state change, so a weighted pick can test every
        candidate before choosing one."""
        if self.state == CLOSED:
            return True
        now = self._clock() if now is None else now
        if self.state == OPEN:
            return (now - self._opened_at >= self.recovery_seconds
                    and self._probes_inflight < self.half_open_probes)
        # Half-open: a free probe slot — OR a leaked one. A probe whose
        # delivery was cancelled/crashed before any outcome was recorded
        # (dispatcher stop mid-POST, client disconnect cancelling the sync
        # handler) never releases its slot; without this time-based escape
        # the backend would stay ejected forever. One cooldown of silence
        # after the last probe began re-opens the slot.
        return (self._probes_inflight < self.half_open_probes
                or now - self._probe_started_at >= self.recovery_seconds)

    def begin_probe(self, now: float | None = None) -> None:
        """The pick landed on this backend while it was open/half-open:
        transition open → half-open (cooldown elapsed, or a forced
        last-resort probe on a fully-dark set) and account the in-flight
        probe so a second pick doesn't stampede the recovering backend."""
        if self.state == CLOSED:
            return
        if self.state == OPEN:
            self.state = HALF_OPEN
            self._probes_inflight = 0
        self._probes_inflight += 1
        self._probe_started_at = (self._clock() if now is None else now)

    # -- outcome recording --------------------------------------------------

    def record_success(self) -> None:
        if self.state == CLOSED:
            self._consecutive = 0
            self._window.append(True)
            return
        if self.state == HALF_OPEN and self._probes_inflight > 0:
            # Probe succeeded (forced all-dark probes also travel through
            # begin_probe, so they land here too): the backend answered —
            # close.
            self._reset()
            return
        # OPEN — or half-open with NO probe in flight: a stale success
        # from a request dispatched BEFORE the trip (concurrent delivery
        # loops). Weak evidence — closing on it would let one straggler
        # 200 cancel the cooldown every time a flapping backend trips,
        # defeating ejection entirely. Ignore; recovery goes through an
        # actual probe's outcome.

    def record_neutral(self) -> None:
        """A backpressure answer (429/503): the backend is alive but
        saturated — neither success nor failure for the breaker, but a
        probe that drew it is RESOLVED (the slot must free, or a single
        503'd probe would eject the backend forever)."""
        if self.state == HALF_OPEN:
            self._probes_inflight = max(0, self._probes_inflight - 1)

    def record_failure(self, now: float | None = None) -> bool:
        """Record one failure. Returns True when THIS call tripped the
        breaker open (callers propagate the event — e.g. the dispatcher
        feeds it to the admission limiter's backoff)."""
        now = self._clock() if now is None else now
        self.last_failure_at = now
        if self.state == CLOSED:
            self._consecutive += 1
            self._window.append(False)
            window_full = len(self._window) == self._window.maxlen
            failures = sum(1 for ok in self._window if not ok)
            if (self._consecutive >= self.failure_threshold
                    or (window_full
                        and failures / len(self._window) >= self.error_rate)):
                self._trip(now)
                return True
            return False
        if self.state == HALF_OPEN:
            # Probe failed: back to open, cooldown restarts from NOW.
            self._trip(now)
            return True
        # Already open: a stale failure from a request dispatched before
        # the trip (staggered timeouts on concurrent loops can dribble in
        # for the whole request_timeout). Statistics only — refreshing the
        # cooldown anchor here would extend ejection far past
        # recovery_seconds on exactly the backends that hang rather than
        # refuse. (Forced probes travel through begin_probe → half-open,
        # so they never land in this branch.)
        return False

    # -- internals ----------------------------------------------------------

    def _trip(self, now: float) -> None:
        self.state = OPEN
        self._opened_at = now
        self._probes_inflight = 0
        self._consecutive = 0
        self._window.clear()
        self.opened_count += 1

    def _reset(self) -> None:
        self.state = CLOSED
        self._consecutive = 0
        self._window.clear()
        self._probes_inflight = 0
