"""Traffic-tuned batch-bucket ladders — THE deriver module.

The static ``(1, 2, 4, ..., 256)`` tuple this module replaces encoded a
guess about traffic; r04 showed a single hand-picked tile ~3×'d
throughput, and PAPERS 2503.01025 / 2503.20074 argue the general point:
device placement and shapes should be *derived from measured cost*, not
configured. Here the measurement is the batcher's own cut sizes:

- ``ShapeHistogram`` keeps a bounded, exponentially-decayed histogram of
  observed batch demand per servable — the PRE-clamp queue length at
  each cut, clamped only to the FACTORY ladder's max, so a swap that
  shrank the top bucket can still witness the larger demand that should
  grow it back (every servable in this codebase declares a fixed
  ``input_shape``, so batch size is the only variable device dimension;
  a shape-variable servable would key this histogram by ``(shape, n)``
  instead);
- ``derive_ladder`` turns a histogram into a bucket ladder minimizing
  expected pad-waste × compile count under a max-programs budget
  (dynamic program over candidate cut points; the configured factory
  ladder is always in the candidate set, so the derived ladder's
  expected pad-waste never exceeds the static ladder's on the same
  histogram whenever the budget admits it);
- ``LadderManager`` owns the loop: observe cuts → re-derive on a period
  → AOT-compile the new ladder in the background (reusing the runtime's
  concurrent-compile warmup path) → atomically swap it in → persist it
  beside the persistent compilation cache so a restarted worker AOT-warms
  the *traffic-tuned* ladder and serves hot from the first request.

Swap safety invariant (tests/test_race_regressions.py): a new ladder is
assigned only after every one of its buckets has a compiled, executed
program — no request is ever padded to a bucket whose first call would
compile on the serving path, and the old ladder's programs are never
evicted, so an in-flight batch cut against the old tuple stays warm too.

AIL012 (``analysis/rules/bucket_literal.py``): any literal bucket/tile
ladder tuple under ``runtime/`` *outside this module* is a lint finding —
the static ladder must not silently come back. Every factory default
lives in the named constants below.

Persistence invalidation rule (docs/device_path.md): the persisted entry
is keyed by a fingerprint of the model's *code identity* (name, version,
input geometry, factory ladder). A ``params_version`` bump (hot weight
reload) does NOT change the fingerprint — the traffic that shaped the
ladder is still the traffic — while a model code/geometry change does,
forcing a re-derive from the factory ladder.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time

import numpy as np

log = logging.getLogger("ai4e_tpu.ladder")

# -- factory ladders (the ONLY literal ladders allowed in runtime/) --------

#: ServableModel's default batch buckets.
DEFAULT_BUCKETS = (1, 2, 4, 8)
#: Image-classifier family default (landcover/species/imagenet-class).
IMAGE_BUCKETS = (1, 16, 64)
#: Detector family default (4× the pixels per example of the classifiers).
DETECTOR_BUCKETS = (1, 8, 16)
#: The static ``ai4e_batch_size`` exposition ladder — kept for ladder-
#: derivation-off batchers so /metrics stays byte-identical to the
#: pre-derivation platform.
EXPOSITION_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
#: Decode-path PROMPT buckets (runtime/kvcache.py): a streaming
#: request's prompt pads to the smallest fitting bucket before prefill,
#: so XLA compiles len(ladder) prefill programs instead of one per
#: prompt length. The decode runtime always appends the KV-cache length
#: as the covering top bucket (every admissible prompt has a compiled
#: program). Same AIL012 discipline as the batch ladders: the literal
#: lives HERE, overridden by AI4E_RUNTIME_DECODE_PROMPT_BUCKETS.
DECODE_PROMPT_BUCKETS = (1, 16, 64)


def _align_up(n: int, multiple: int) -> int:
    """Smallest multiple of ``multiple`` >= n (SPMD bucket rounding —
    same arithmetic as ``parallel.sharding.pad_to_multiple``, local so
    this module stays importable without jax)."""
    if multiple <= 1:
        return int(n)
    return int(math.ceil(n / multiple) * multiple)


def exposition_buckets(servables) -> tuple[int, ...]:
    """``ai4e_batch_size`` exposition buckets built from the servables'
    OWN ladders (satellite: the static copy at batcher construction
    would drift the moment ladders are derived). Falls back to the
    static exposition ladder when no servable is registered yet."""
    union = sorted({int(b) for s in servables for b in s.batch_buckets})
    return tuple(union) if union else EXPOSITION_BUCKETS


# -- observed-shape histogram ----------------------------------------------


class ShapeHistogram:
    """Bounded, exponentially-decayed histogram of observed batch-cut
    sizes. ``window_s`` is the half-life: a cut size not seen for one
    window carries half its weight, so the ladder follows traffic shifts
    instead of averaging over the process lifetime. Bounded at
    ``max_sizes`` distinct sizes (lowest-weight entry evicted) so an
    adversarial size sweep cannot grow it without bound. Thread-safe:
    observed from the event loop, snapshotted from the deriver thread."""

    def __init__(self, window_s: float = 300.0, max_sizes: int = 256,
                 clock=time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = window_s
        self.max_sizes = max_sizes
        self._clock = clock
        self._lock = threading.Lock()
        self._weights: dict[int, float] = {}
        self._stamps: dict[int, float] = {}
        self._count = 0  # raw observations, never decayed

    def _decayed(self, size: int, now: float) -> float:
        w = self._weights.get(size, 0.0)
        if not w:
            return 0.0
        return w * 0.5 ** ((now - self._stamps[size]) / self.window_s)

    def observe(self, n: int, weight: float = 1.0) -> None:
        if n < 1:
            return
        now = self._clock()
        with self._lock:
            self._count += 1
            self._weights[n] = self._decayed(n, now) + weight
            self._stamps[n] = now
            if len(self._weights) > self.max_sizes:
                victim = min(self._weights,
                             key=lambda s: self._decayed(s, now))
                del self._weights[victim]
                del self._stamps[victim]

    def snapshot(self) -> dict[int, float]:
        """Decayed weights per size; entries below 1e-6 dropped."""
        now = self._clock()
        with self._lock:
            return {s: w for s in self._weights
                    if (w := self._decayed(s, now)) > 1e-6}

    @property
    def observations(self) -> int:
        return self._count


# -- derivation ------------------------------------------------------------


def expected_pad_waste(ladder, hist: dict[int, float]) -> float:
    """Expected padded slots per cut under ``ladder``: each observed size
    pads to the smallest bucket >= it (sizes above the largest bucket
    clamp — the batcher never cuts past ``max_bucket``, so they only
    appear when comparing a foreign histogram against a smaller ladder,
    and a clamped cut pads nothing)."""
    buckets = sorted(ladder)
    total = 0.0
    for s, w in hist.items():
        b = next((b for b in buckets if b >= s), None)
        if b is not None:
            total += w * (b - s)
    return total


def derive_ladder(hist: dict[int, float], *, baseline,
                  max_programs: int = 16, align: int = 1
                  ) -> tuple[int, ...]:
    """Derive a bucket ladder from an observed cut-size histogram.

    Objective: minimize expected pad-waste × program count, subject to
    at most ``max_programs`` buckets — more programs cost compile time,
    AOT-warmup time, and device program memory, so zero-waste ladders
    prefer the fewest buckets achieving it. Guarantees (property-tested
    in tests/test_ladder.py):

    - strictly ascending (monotone) buckets, all multiples of ``align``
      (the mesh data-axis size — the SPMD divisibility rule
      ``ModelRuntime.register`` applies to configured ladders);
    - the largest bucket covers the observed max;
    - expected pad-waste <= the ``baseline`` (static) ladder's on the
      same histogram whenever the budget admits the baseline itself
      (the baseline's buckets are always candidates).

    An empty histogram returns the aligned baseline unchanged.
    """
    if max_programs < 1:
        raise ValueError(f"max_programs must be >= 1, got {max_programs}")
    hist = {int(s): float(w) for s, w in hist.items()
            if s >= 1 and w > 0}
    base = tuple(sorted({_align_up(b, align) for b in baseline}))
    if not hist:
        return base
    max_obs = max(hist)
    cover = _align_up(max_obs, align)
    # Candidate cut points: every aligned observed size, plus the
    # baseline's buckets up to the covering one — including the baseline
    # makes "the static ladder, trimmed" a reachable DP solution, which
    # is what makes the waste-vs-baseline guarantee unconditional when
    # max_programs admits it.
    cand = sorted({_align_up(s, align) for s in hist}
                  | {b for b in base if b <= cover} | {cover})
    n = len(cand)
    # Prefix sums over observed weight per candidate index: sizes are
    # assigned to the smallest chosen bucket >= them, so the waste of
    # choosing cand[i] after cand[j] is sum over sizes in (cand[j],
    # cand[i]] of w*(cand[i] - s).
    pw = [0.0] * (n + 1)   # cumulative weight of sizes <= cand[i-1]
    pws = [0.0] * (n + 1)  # cumulative weight*size
    sizes = sorted(hist)
    si = 0
    for i, c in enumerate(cand):
        pw[i + 1], pws[i + 1] = pw[i], pws[i]
        while si < len(sizes) and sizes[si] <= c:
            pw[i + 1] += hist[sizes[si]]
            pws[i + 1] += hist[sizes[si]] * sizes[si]
            si += 1

    def seg_cost(j: int, i: int) -> float:
        # Waste of sizes in (cand[j-1], cand[i-1]] padded to cand[i-1];
        # j == 0 means "no smaller bucket chosen".
        return cand[i - 1] * (pw[i] - pw[j]) - (pws[i] - pws[j])

    top = cand.index(cover) + 1  # 1-based index of the forced top bucket
    kmax = min(max_programs, top)
    INF = float("inf")
    # best[k][i]: min waste covering all sizes <= cand[i-1] with exactly
    # k buckets, the largest being cand[i-1].
    best = [[INF] * (top + 1) for _ in range(kmax + 1)]
    parent: dict[tuple[int, int], int] = {}
    for i in range(1, top + 1):
        best[1][i] = seg_cost(0, i)
    for k in range(2, kmax + 1):
        for i in range(k, top + 1):
            for j in range(k - 1, i):
                w = best[k - 1][j] + seg_cost(j, i)
                if w < best[k][i]:
                    best[k][i] = w
                    parent[(k, i)] = j
    waste_at = {k: best[k][top] for k in range(1, kmax + 1)
                if best[k][top] < INF}
    base_waste = expected_pad_waste(base, hist)
    # Never do worse than the static ladder when the budget allows
    # matching it; within the admissible set, minimize waste × count
    # (ties → fewer programs, then less waste).
    admissible = {k: w for k, w in waste_at.items()
                  if w <= base_waste + 1e-9} or waste_at
    k_star = min(admissible, key=lambda k: (admissible[k] * k, k,
                                            admissible[k]))
    chosen = []
    k, i = k_star, top
    while k >= 1:
        chosen.append(cand[i - 1])
        i = parent.get((k, i), 0)
        k -= 1
    return tuple(sorted(chosen))


# -- persistence (beside the persistent compilation cache) -----------------


def servable_fingerprint(servable) -> str:
    """Code-identity fingerprint for persisted-ladder validity: name,
    declared version, input geometry. Does NOT include
    ``params_version`` — a hot weight reload keeps the ladder valid
    (same traffic, same shapes) — and cannot include the factory ladder
    (at persist time ``batch_buckets`` already holds the DERIVED
    ladder); a deliberate factory-ladder change is instead caught at
    ``LadderManager.restore`` by comparing the entry's recorded
    ``baseline`` against the servable's registered buckets."""
    dtype = np.dtype(servable.input_dtype).name
    return "|".join([
        servable.name, str(servable.version),
        "x".join(str(d) for d in servable.input_shape), dtype,
    ])


def load_ladders(path: str) -> dict:
    """Persisted ladder entries ({model: {fingerprint, baseline, buckets,
    generation}}); {} on a missing or unreadable file — a corrupt ladder
    file must never block a worker boot, the factory ladder serves."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        return data if isinstance(data, dict) else {}
    except (OSError, ValueError):
        return {}


def save_ladders(path: str, entries: dict) -> None:
    """Atomic write (tmp + rename) — a crash mid-persist leaves the
    previous file intact, same discipline as every durable artifact."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(entries, fh, sort_keys=True)
    os.replace(tmp, path)


# -- the manager -----------------------------------------------------------


class LadderManager:
    """Owns per-servable cut histograms and the derive→compile→swap→
    persist loop. The batcher calls ``observe_cut`` at every batch cut;
    every ``period_s`` a background thread re-derives, AOT-compiles any
    new buckets through the runtime's concurrent-compile path, and
    atomically swaps the servable's ladder (``ModelRuntime.apply_ladder``
    refuses a bucket without an executed program — the swap-safety
    invariant). ``dwell_s`` bounds swap churn. All knobs ride
    ``AI4E_RUNTIME_LADDER_*`` (docs/config.md)."""

    def __init__(self, runtime, *, window_s: float = 300.0,
                 max_programs: int = 16, period_s: float = 60.0,
                 dwell_s: float = 120.0, min_observations: int = 32,
                 persist_path: str | None = None, metrics=None,
                 clock=time.monotonic):
        from ..metrics import DEFAULT_REGISTRY
        self.runtime = runtime
        self.window_s = window_s
        self.max_programs = max_programs
        self.period_s = period_s
        self.dwell_s = dwell_s
        self.min_observations = min_observations
        self.persist_path = persist_path
        self._clock = clock
        self._lock = threading.Lock()
        # Serializes the load-modify-write of the ladder file: two
        # models' deriver threads swapping in the same period would
        # otherwise each read a stale snapshot and the last writer
        # would drop the other's entry (restart would then warm that
        # model's factory ladder — the restart-serves-hot contract).
        self._persist_lock = threading.Lock()
        self._hists: dict[str, ShapeHistogram] = {}
        self._baseline: dict[str, tuple[int, ...]] = {}
        self._generation: dict[str, int] = {}
        self._last_swap: dict[str, float] = {}
        self._next_check: dict[str, float] = {}
        self._busy: set[str] = set()
        self.metrics = metrics or DEFAULT_REGISTRY
        self._gen_gauge = self.metrics.gauge(
            "ai4e_ladder_generation",
            "Derived-ladder generation per model (0 = factory ladder)")
        self._buckets_gauge = self.metrics.gauge(
            "ai4e_ladder_buckets",
            "Compiled bucket count in the serving ladder per model")
        self._derives_total = self.metrics.counter(
            "ai4e_ladder_derives_total",
            "Ladder derivation attempts by model and outcome "
            "(swapped/unchanged/skipped/failed)")
        self._pad_waste_gauge = self.metrics.gauge(
            "ai4e_ladder_expected_pad_ratio",
            "Expected padded-slots / occupied-slots of the serving ladder "
            "on the current cut-size histogram, per model")

    # -- startup restore ---------------------------------------------------

    def restore(self) -> dict[str, tuple[int, ...]]:
        """Apply persisted derived ladders to registered servables —
        called BEFORE ``warmup`` so a restarted worker AOT-warms the
        traffic-tuned ladder, not the factory default, and its first
        serving call stamps ``execute``, never ``compile``. Entries with
        a stale fingerprint (model code changed) or a mesh whose
        alignment no longer admits the persisted buckets are discarded.
        Returns {model: restored buckets}."""
        restored: dict[str, tuple[int, ...]] = {}
        if not self.persist_path:
            return restored
        entries = load_ladders(self.persist_path)
        align = getattr(self.runtime, "data_axis_size", 1)
        for name, servable in self.runtime.models.items():
            self._adopt(name)
            entry = entries.get(name)
            if not isinstance(entry, dict):
                continue
            if entry.get("fingerprint") != servable_fingerprint(servable):
                continue
            if (tuple(int(b) for b in entry.get("baseline", ()))
                    != tuple(servable.batch_buckets)):
                # The operator changed the FACTORY ladder since this
                # entry persisted (docs/device_path.md invalidation
                # rule): the new factory buckets must serve — and be
                # re-derivable from — fresh traffic, not be shadowed by
                # a ladder tuned under the old config.
                continue
            buckets = tuple(int(b) for b in entry.get("buckets", ()))
            if not buckets or any(b % max(1, align) for b in buckets):
                continue
            servable.batch_buckets = tuple(sorted(set(buckets)))
            self._generation[name] = int(entry.get("generation", 1))
            self._gen_gauge.set(self._generation[name], model=name)
            self._buckets_gauge.set(len(servable.batch_buckets), model=name)
            restored[name] = servable.batch_buckets
            log.info("ladder restore %s: generation %d, buckets %s",
                     name, self._generation[name], servable.batch_buckets)
        return restored

    # -- hot-path surface --------------------------------------------------

    def _adopt(self, name: str) -> None:
        if name in self._baseline:
            return
        servable = self.runtime.models[name]
        self._baseline[name] = tuple(servable.batch_buckets)
        self._generation.setdefault(name, 0)
        self._hists[name] = ShapeHistogram(window_s=self.window_s,
                                           clock=self._clock)
        self._next_check[name] = self._clock() + self.period_s
        self._gen_gauge.set(self._generation[name], model=name)
        self._buckets_gauge.set(len(servable.batch_buckets), model=name)

    def observe_cut(self, name: str, n: int) -> None:
        """One batch cut's PRE-clamp demand of ``n`` examples — O(1),
        called by the batcher on the event loop. The demand is clamped
        to the FACTORY ladder's max (the operator-configured memory
        bound), NOT the current derived ladder's — otherwise a swap that
        shrank the top bucket would cap every later observation at it
        and the ladder could only ever ratchet down. Kicks the
        background deriver at most once per ``period_s`` per model;
        derivation/compile never runs here."""
        if name not in self._baseline:
            self._adopt(name)
        self._hists[name].observe(min(n, max(self._baseline[name])))
        now = self._clock()
        with self._lock:
            if now < self._next_check[name] or name in self._busy:
                return
            self._next_check[name] = now + self.period_s
            self._busy.add(name)
        threading.Thread(target=self._derive_in_background, args=(name,),
                         name=f"ladder-derive-{name}", daemon=True).start()

    # -- deriver -----------------------------------------------------------

    def _derive_in_background(self, name: str) -> None:
        try:
            outcome = self.derive_now(name)
            log.debug("ladder derive %s: %s", name, outcome)
        except Exception:  # noqa: BLE001 — counted outcome=failed below; a deriver crash must never reach serving
            self._derives_total.inc(model=name, outcome="failed")
            log.exception("ladder derivation failed for %s "
                          "(old ladder keeps serving)", name)
        finally:
            with self._lock:
                self._busy.discard(name)

    def derive_now(self, name: str) -> str:
        """One derivation pass (synchronous — the background thread's
        body, callable directly from tests/bench): snapshot the
        histogram, derive, AOT-compile new buckets, swap, persist.
        Returns the outcome recorded on ``ai4e_ladder_derives_total``."""
        self._adopt(name)
        hist_obj = self._hists[name]
        hist = hist_obj.snapshot()
        if hist_obj.observations < self.min_observations or not hist:
            self._derives_total.inc(model=name, outcome="skipped")
            return "skipped"
        align = getattr(self.runtime, "data_axis_size", 1)
        new = derive_ladder(hist, baseline=self._baseline[name],
                            max_programs=self.max_programs, align=align)
        current = tuple(self.runtime.models[name].batch_buckets)
        if new == current:
            self._pad_waste_gauge.set(self._expected_ratio(current, hist),
                                      model=name)
            self._derives_total.inc(model=name, outcome="unchanged")
            return "unchanged"
        now = self._clock()
        last = self._last_swap.get(name)
        if last is not None and now - last < self.dwell_s:
            # The gauge documents the SERVING ladder's expected ratio —
            # keep it tracking `current`, not the candidate that did not
            # swap in (a skipped/failed derive must not show a phantom
            # improvement next to ai4e_batch_pad_ratio).
            self._pad_waste_gauge.set(self._expected_ratio(current, hist),
                                      model=name)
            self._derives_total.inc(model=name, outcome="skipped")
            return "skipped"
        # AOT-compile + warm-execute every new bucket FIRST (background
        # thread, off the serving path), then the swap is one attribute
        # assignment — in-flight cuts hold the old tuple, whose programs
        # stay compiled.
        prepared = self.runtime.prepare_buckets(name, new)
        self.runtime.apply_ladder(name, prepared)
        self._pad_waste_gauge.set(self._expected_ratio(prepared, hist),
                                  model=name)
        self._generation[name] = self._generation.get(name, 0) + 1
        self._last_swap[name] = self._clock()
        self._gen_gauge.set(self._generation[name], model=name)
        self._buckets_gauge.set(len(prepared), model=name)
        self._derives_total.inc(model=name, outcome="swapped")
        log.info("ladder swap %s: generation %d, %s -> %s", name,
                 self._generation[name], current, prepared)
        self._persist(name, prepared)
        return "swapped"

    @staticmethod
    def _expected_ratio(ladder, hist: dict[int, float]) -> float:
        occupied = sum(s * w for s, w in hist.items())
        if occupied <= 0:
            return 0.0
        return expected_pad_waste(ladder, hist) / occupied

    def _persist(self, name: str, buckets: tuple[int, ...]) -> None:
        if not self.persist_path:
            return
        servable = self.runtime.models[name]
        with self._persist_lock:
            entries = load_ladders(self.persist_path)
            entries[name] = {
                "fingerprint": servable_fingerprint(servable),
                "baseline": list(self._baseline[name]),
                "buckets": list(buckets),
                "generation": self._generation[name],
            }
            try:
                save_ladders(self.persist_path, entries)
            except OSError:
                log.warning("ladder persist failed for %s at %s (the "
                            "swap is live; a restart re-derives)", name,
                            self.persist_path, exc_info=True)

    # -- introspection (bench / tests) -------------------------------------

    def generation(self, name: str) -> int:
        return self._generation.get(name, 0)

    def baseline(self, name: str) -> tuple[int, ...]:
        return self._baseline.get(name, ())
