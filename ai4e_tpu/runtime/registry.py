"""Model registry — servable JAX models with bucketed compiled programs.

The reference's "model registry" is a container registry: each model API is an
opaque Docker image lazy-loading weights at startup (``APIs/Charts/templates/
async-gpu/templates/deployment.yaml:14-55``). Here a servable is code+params
in-process: an apply function compiled per (batch-bucket) shape onto the
device mesh, with explicit warmup (the compile-time management SURVEY.md §7
lists as a hard part — containers lazy-load; TPU programs must precompile).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ladder import DEFAULT_BUCKETS
# The one sanctioned device→host fetch (AIL014: every other transfer on
# the serving path must carry an explicit placement).
from .mesh.placement import fetch_to_host

log = logging.getLogger("ai4e_tpu.runtime")

Preprocess = Callable[[bytes, str], np.ndarray]
Postprocess = Callable[[Any], Any]


@dataclass
class ServableModel:
    """One deployable model API.

    - ``apply_fn(params, batch) -> outputs``: pure function of a dense batch;
    - ``preprocess(body, content_type) -> example``: request payload → one
      example array of ``input_shape`` (raises ValueError on bad input — that
      fails one task, never a batch);
    - ``postprocess(example_outputs) -> result``: one example's slice of the
      outputs → JSON-able result.
    - ``batch_buckets``: allowed batch sizes, ascending. Requests are padded
      up to the smallest fitting bucket so XLA compiles exactly
      ``len(batch_buckets)`` programs per model.
    """

    name: str
    apply_fn: Callable
    params: Any
    input_shape: tuple[int, ...]
    preprocess: Preprocess
    postprocess: Postprocess
    batch_buckets: tuple[int, ...] = DEFAULT_BUCKETS
    input_dtype: Any = np.float32
    version: str = "1.0"
    # Weights provenance for hot reload: the checkpoint this servable's
    # params were restored from (None = init/in-memory weights), and a
    # monotonic version bumped by every successful reload_params — the
    # /models introspection exposes both so operators can confirm a
    # rollout landed.
    checkpoint_path: str | None = None
    params_version: int = 1
    # Rollout generation (rollout/, docs/deployment.md): which fleet-wide
    # deploy this servable's weights belong to. params_version is a local
    # monotonic swap counter; generation is the cross-replica coordinate
    # the canary split routes on — the reload verb sets it from the
    # controller's payload, /models exposes it.
    generation: int = 1
    # Param-path → PartitionSpec rules applied at register() — how a family
    # declares model-parallel placement (e.g. MoE experts over ep) that must
    # survive the runtime's own param placement.
    param_sharding_rules: dict | None = None
    # Batch-STACK ingestion for servables whose device input shape differs
    # from the natural payload shape (e.g. the yuv420 wire's flat planes):
    # stacks arrive as (N, *stack_item_shape) in stack_item_dtype and each
    # item passes through stack_adapter to become an input_shape example.
    # None = stacks match input_shape directly.
    stack_item_shape: tuple[int, ...] | None = None
    stack_item_dtype: Any = None
    stack_adapter: Callable | None = None
    # Value-level validation of the RAW decoded stack, before any dtype
    # cast (token servables reject floats / out-of-range ids here — a
    # post-cast check would pass ids that wrapped into range).
    stack_validator: Callable | None = None
    # Inverse for HOST consumers of a preprocessed example (pipeline
    # handoffs crop the stage's input image): example → natural image.
    # None = the example already is the natural payload.
    example_decoder: Callable | None = None
    _compiled: Callable | None = field(default=None, repr=False)
    _batch_sharding: Any = field(default=None, repr=False)

    def bucket_for(self, n: int) -> int:
        for b in self.batch_buckets:
            if b >= n:
                return b
        return self.batch_buckets[-1]

    @property
    def max_bucket(self) -> int:
        return self.batch_buckets[-1]


class ModelRuntime:
    """Owns the mesh, compiled programs, and parameter placement.

    This is the slot where the reference's CUDA-container black box becomes a
    first-class runtime: ``jit`` with a batch sharding over the mesh's data
    axes; XLA lays matmuls/convs onto the MXU and inserts ICI collectives for
    any model-parallel params.
    """

    def __init__(self, mesh: Mesh | None = None, donate_batch: bool = False,
                 replicate_outputs: bool | None = None):
        from ..parallel.sharding import make_mesh
        self.mesh = mesh if mesh is not None else make_mesh()
        self.models: dict[str, ServableModel] = {}
        self._donate = donate_batch
        # Multi-host (mesh spans processes): outputs must come back fully
        # replicated so every process — in particular the primary serving
        # results — can read them without a cross-host gather on the response
        # path (inference outputs are small). Single-host: XLA's choice.
        if replicate_outputs is None:
            replicate_outputs = jax.process_count() > 1
        self._replicate_outputs = replicate_outputs
        # (model, padded-batch-size) programs this process has executed —
        # run_batch_phases labels a first execution's device time
        # ``compile`` instead of ``execute`` (with a persistent
        # compilation cache the "compile" is a cache load, still the
        # first-call stall worth naming).
        self._executed_shapes: set[tuple[str, int]] = set()

    @property
    def data_axis_size(self) -> int:
        return (self.mesh.shape["dp"] * self.mesh.shape["fsdp"])

    def register(self, servable: ServableModel,
                 param_sharding_rules: dict | None = None) -> ServableModel:
        """Place params on the mesh and build per-bucket compiled fns."""
        from ..parallel.sharding import pad_to_multiple, shard_params
        rules = (param_sharding_rules if param_sharding_rules is not None
                 else servable.param_sharding_rules)
        servable.params = shard_params(servable.params, self.mesh, rules)
        # SPMD constraint: every batch bucket must divide evenly over the
        # data axes, so buckets round up to mesh multiples (on 1 chip they
        # stay as configured; on a v5e-4 dp mesh they become multiples of 4).
        servable.batch_buckets = tuple(sorted({
            pad_to_multiple(b, self.data_axis_size)
            for b in servable.batch_buckets}))
        batch_sharding = NamedSharding(
            self.mesh, P(("dp", "fsdp"), *([None] * len(servable.input_shape))))
        servable._batch_sharding = batch_sharding

        servable._compiled = jax.jit(
            servable.apply_fn,
            in_shardings=(None, batch_sharding),
            # A single sharding as out_shardings applies to every output leaf.
            out_shardings=(NamedSharding(self.mesh, P())
                           if self._replicate_outputs else None),
            donate_argnums=(1,) if self._donate else (),
        )
        self.models[servable.name] = servable
        return servable

    def warmup(self, names: list[str] | None = None,
               parallel: bool = True) -> dict[str, float]:
        """Precompile every (model, bucket) program. Returns compile seconds
        per model — exported as a metric so pod-start latency is visible.

        ``parallel`` (default): all (model, bucket) programs are AOT
        lowered+compiled concurrently first — XLA releases the GIL during
        compilation, and on a remote-attached TPU each compile is a server
        round trip, so N programs cost ~max not ~sum — then each bucket
        executes once through ``run_batch`` (hitting the now-warm caches)
        so the execute path is proven too. Serial mode is kept for
        multi-host runtimes, where every process must enter compiles in
        the same order."""
        todo = [(name, servable) for name, servable in self.models.items()
                if names is None or name in names]

        compile_s = 0.0
        if parallel and not jax.config.jax_compilation_cache_dir:
            # AOT lower().compile() does NOT seed the jit dispatch cache —
            # only the persistent compilation cache carries its work over to
            # the run_batch pass. Without one, parallel mode would compile
            # every program twice; serial is strictly better then.
            log.warning("warmup: persistent compilation cache not enabled "
                        "(enable_compilation_cache(); see docs/"
                        "device_path.md#compile-cache-and-aot-warmup); "
                        "using serial warmup")
            parallel = False
        if parallel and jax.process_count() == 1:
            jobs = [(s, b) for _, s in todo for b in s.batch_buckets]
            compile_s = self._aot_compile(jobs)
            log.info("warmup: %d programs compiled concurrently in %.1fs",
                     len(jobs), compile_s)

        # The concurrent compile phase serves every model at once, so its
        # wall time is amortised evenly across the per-model figures — the
        # returned dict must keep meaning "pod-start seconds attributable
        # to this model", the metric operators watch.
        times: dict[str, float] = {}
        for name, servable in todo:
            t0 = time.perf_counter()
            for bucket in servable.batch_buckets:
                dummy = np.zeros((bucket, *servable.input_shape),
                                 servable.input_dtype)
                # Through run_batch so multi-host input conversion applies.
                self.run_batch(name, dummy)
            times[name] = (time.perf_counter() - t0
                           + compile_s / max(1, len(todo)))
            log.info("warmup %s: %d buckets in %.1fs", name,
                     len(servable.batch_buckets), times[name])
        return times

    def _aot_compile(self, jobs) -> float:
        """Concurrently lower+compile ``(servable, bucket)`` programs —
        the warmup fast path, reused by ``prepare_buckets`` so a derived
        ladder's background compile costs ~max, not ~sum, of its
        programs. Returns wall seconds; surfaces the first compile
        error."""
        from concurrent.futures import ThreadPoolExecutor

        def compile_one(servable, bucket):
            dummy = jax.ShapeDtypeStruct(
                (bucket, *servable.input_shape),
                np.dtype(servable.input_dtype))
            servable._compiled.lower(servable.params, dummy).compile()

        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=min(8, max(1, len(jobs)))) as ex:
            for f in [ex.submit(compile_one, s, b) for s, b in jobs]:
                f.result()
        return time.perf_counter() - t0

    def prepare_buckets(self, name: str, buckets) -> tuple[int, ...]:
        """Compile + warm-execute a candidate ladder for ``name`` WITHOUT
        swapping it in (the ladder deriver's background step,
        docs/device_path.md). Buckets are rounded up to the mesh's data-
        axis multiple (same SPMD rule ``register`` applies), AOT-compiled
        concurrently when the persistent compilation cache is enabled,
        and each previously-unseen bucket is executed once through
        ``run_batch`` so the jit dispatch cache is warm and the program
        is marked executed — after this returns, ``apply_ladder`` can
        swap with zero serving-path compiles. Returns the aligned tuple
        to pass to ``apply_ladder``."""
        from ..parallel.sharding import pad_to_multiple
        servable = self.models[name]
        aligned = tuple(sorted({
            pad_to_multiple(int(b), self.data_axis_size) for b in buckets}))
        if not aligned:
            raise ValueError(f"empty ladder for {name}")
        todo = [b for b in aligned
                if (name, b) not in self._executed_shapes]
        if not todo:
            return aligned
        if jax.process_count() == 1 and jax.config.jax_compilation_cache_dir:
            self._aot_compile([(servable, b) for b in todo])
        for bucket in todo:
            dummy = np.zeros((bucket, *servable.input_shape),
                             servable.input_dtype)
            self.run_batch(name, dummy)
        return aligned

    def apply_ladder(self, name: str, buckets) -> tuple[int, ...]:
        """Atomically swap ``name``'s serving ladder to ``buckets`` (the
        tuple ``prepare_buckets`` returned). The swap is one attribute
        assignment — in-flight batch cuts hold the old tuple, whose
        programs stay compiled (``_executed_shapes`` is append-only), so
        no request on either side of the swap ever pads to a bucket
        without a compiled program. Refuses any bucket that has not been
        executed — the invariant the ladder-swap interleaving regression
        (tests/test_race_regressions.py) pins."""
        servable = self.models[name]
        aligned = tuple(sorted({int(b) for b in buckets}))
        missing = [b for b in aligned
                   if (name, b) not in self._executed_shapes]
        if missing:
            raise RuntimeError(
                f"apply_ladder({name}): buckets {missing} have no "
                f"executed program — call prepare_buckets first")
        servable.batch_buckets = aligned
        return aligned

    def reload_params(self, name: str, new_params) -> "ServableModel":
        """Hot-swap a registered servable's weights — zero-downtime model
        update (the reference rolls whole containers for this,
        ``APIs/Charts/templates/async-gpu``; here the jitted programs take
        params as an ARGUMENT, so new weights need no recompile).

        The new tree must match the current one exactly (structure, shapes,
        dtypes) — reload updates weights, never architecture; a geometry
        change is a new model spec + restart. The swap is a single attribute
        assignment: in-flight batches already hold the old reference and
        complete on it; every later ``run_batch`` picks up the new params.
        """
        from ..parallel.sharding import shard_params
        servable = self.models[name]  # KeyError → caller's 404

        def spec_of(tree):
            return jax.tree.map(
                lambda a: (tuple(a.shape), jnp.result_type(a).name), tree)

        old_spec, new_spec = spec_of(servable.params), spec_of(new_params)
        if old_spec != new_spec:
            raise ValueError(
                f"checkpoint tree does not match the served model: "
                f"served {old_spec} vs reload {new_spec}")
        placed = shard_params(new_params, self.mesh,
                              servable.param_sharding_rules)
        servable.params = placed
        servable.params_version += 1
        return servable

    def run_batch(self, name: str, batch: np.ndarray):
        """Execute one padded batch; blocking (call from an executor)."""
        servable = self.models[name]
        if jax.process_count() > 1 and isinstance(batch, np.ndarray):
            # A raw numpy batch on a multi-host slice means every process
            # holds the identical full array (warmup dummies); carve out this
            # process's shards to form the global device array the multi-host
            # jit requires. Serving batches arrive pre-assembled as global
            # jax.Arrays from MultihostRuntime's sharded ingestion.
            batch = jax.make_array_from_process_local_data(
                servable._batch_sharding, batch, global_shape=batch.shape)
        out = servable._compiled(servable.params, batch)
        # Mark the program executed for the phase decomposition's
        # compile-vs-execute labeling: warmup drives every bucket through
        # HERE, so a warmed worker's first phased serving call reports
        # ``execute``, not a phantom ``compile``.
        self._executed_shapes.add((name, batch.shape[0]))
        return fetch_to_host(out)

    def run_batch_report(self, name: str, batch: np.ndarray
                         ) -> tuple[object, frozenset]:
        """``run_batch`` plus a poisoned-rows report — uniform surface with
        ``MultihostRuntime.run_batch_report`` so the batcher can fail exactly
        the rows a degraded follower invalidated. A single-runtime execution
        has no partial-degrade mode: the set is always empty (a device
        failure raises and fails the whole batch)."""
        return self.run_batch(name, batch), frozenset()

    def run_batch_phases(self, name: str, batch: np.ndarray
                         ) -> tuple[object, frozenset, dict[str, float]]:
        """``run_batch_report`` with the device boundary decomposed into
        measured phases (observability/, docs/observability.md):

        - ``h2d``: explicit ``device_put`` of the padded batch onto the
          mesh sharding, blocked until resident;
        - ``execute``: the compiled program on the already-resident
          batch, blocked until outputs materialize — reported as
          ``compile`` instead when this is the FIRST execution of the
          (model, bucket) program in this process (warmup normally eats
          these; a serving-path compile is exactly the stall an operator
          needs to see named);
        - ``d2h``: ``device_get`` of the outputs.

        Returns ``(host_outputs, poisoned_rows, {phase: seconds})``.
        Single-host only — the batcher falls back to ``run_batch_report``
        (one undecomposed ``execute``) on runtimes without this method
        (multi-host mirrors every call and must not diverge per phase).
        """
        servable = self.models[name]
        if jax.process_count() > 1:
            # Phase decomposition would desynchronise the follower
            # mirror-loop's single-call contract; undecomposed fallback.
            out, poisoned = self.run_batch_report(name, batch)
            return out, poisoned, {}
        phases: dict[str, float] = {}
        t0 = time.perf_counter()
        device_batch = jax.device_put(batch, servable._batch_sharding)
        jax.block_until_ready(device_batch)
        phases["h2d"] = time.perf_counter() - t0
        first = (name, batch.shape[0]) not in self._executed_shapes
        t0 = time.perf_counter()
        out = servable._compiled(servable.params, device_batch)
        jax.block_until_ready(out)
        phases["compile" if first else "execute"] = (
            time.perf_counter() - t0)
        self._executed_shapes.add((name, batch.shape[0]))
        t0 = time.perf_counter()
        host = fetch_to_host(out)
        phases["d2h"] = time.perf_counter() - t0
        return host, frozenset(), phases

    # -- split-phase surface (double-buffered batcher) ---------------------
    #
    # The three device-boundary steps of run_batch_phases as separate
    # blocking calls, each returning its (perf-counter start, end) wall
    # window — the MicroBatcher's double-buffered path runs them on
    # separate single-thread executors so batch N+1's h2d genuinely
    # overlaps batch N's execute and batch N's d2h overlaps batch N+1's
    # execute (docs/device_path.md#double-buffered-transfers). Single-
    # host only: the batcher falls back to the fused path on runtimes
    # without ``supports_split_phases`` (MultihostRuntime mirrors every
    # call and must not diverge per phase).

    def supports_split_phases(self) -> bool:
        return jax.process_count() == 1

    def h2d_resident(self, name: str, batch: np.ndarray):
        """``device_put`` the padded batch onto the mesh sharding,
        blocked until resident. Returns ``(device_batch, (t0, t1))``."""
        servable = self.models[name]
        t0 = time.perf_counter()
        device_batch = jax.device_put(batch, servable._batch_sharding)
        jax.block_until_ready(device_batch)
        return device_batch, (t0, time.perf_counter())

    def execute_resident(self, name: str, device_batch):
        """Run the compiled program on an already-resident batch, blocked
        until outputs materialize on device. Returns ``(device_outputs,
        label, (t0, t1))`` where label is ``"compile"`` on the first
        execution of the (model, bucket) program in this process —
        warmup normally eats these — else ``"execute"``."""
        servable = self.models[name]
        key = (name, device_batch.shape[0])
        first = key not in self._executed_shapes
        t0 = time.perf_counter()
        out = servable._compiled(servable.params, device_batch)
        jax.block_until_ready(out)
        self._executed_shapes.add(key)
        return out, ("compile" if first else "execute"), (
            t0, time.perf_counter())

    def fetch_resident(self, out):
        """``device_get`` the outputs. Returns ``(host_outputs,
        (t0, t1))``."""
        t0 = time.perf_counter()
        host = fetch_to_host(out)
        return host, (t0, time.perf_counter())


def enable_compilation_cache(path: str = "/tmp/ai4e_tpu_xla_cache") -> None:
    """Persistent XLA compilation cache: pod restarts skip recompiles (the
    warmup-at-start requirement in SURVEY.md §7 hard parts).

    XLA:CPU entries are AOT machine code whose cache key does NOT include the
    host's CPU features — an entry compiled on another machine loads with a
    "could lead to SIGILL" warning. The cache dir is therefore keyed by the
    host's CPU identity (machine arch + feature flags). Same-host processes —
    the case that matters: prewarm subprocess → bench, pod restarts — still
    share the cache. Keying unconditionally (rather than only for the CPU
    backend) avoids initializing a JAX backend here, which would break
    ``jax.distributed.initialize`` for callers like ``cli.build_worker`` that
    enable the cache before bringing up the multi-host data plane.
    """
    import hashlib
    import platform
    ident = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            # x86 spells it "flags", aarch64 "Features"
            ident += next((l for l in f
                           if l.lower().startswith(("flags", "features"))), "")
    except OSError:
        pass
    # Key *inside* the configured dir so an operator-mounted persistent
    # volume at ``path`` still holds the cache across pod restarts.
    import os
    path = os.path.join(path, hashlib.sha1(ident.encode()).hexdigest()[:12])
    jax.config.update("jax_compilation_cache_dir", path)
    # Persist everything, including sub-second programs: on a remote-attached
    # TPU every compile is a server round trip (PALLAS_AXON_REMOTE_COMPILE),
    # so even trivial reshape/convert programs cost ~0.5-1 s each on a cold
    # process — a dozen of them is half the warmup. Disk cost is a few KB.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
