"""Inference worker — binds servable models to APIService endpoints.

The per-model GPU container of the reference (``Containers/base-py`` + user
model code) becomes: one APIService with a sync and an async endpoint per
servable, both feeding the shared micro-batcher. The task semantics are
identical to the reference's (``ai4e_service.py:158-213``): sync returns the
result inline; async drives the task created→running→completed/failed and
stores the result on the task store.
"""

from __future__ import annotations

import asyncio
import inspect
import json
import logging
import time

import numpy as np

from ..admission.deadline import (SHED_REASON_HEADER, DeadlineExceeded,
                                  expired, expired_status, priority_name,
                                  shed_reason, worker_admission_kwargs)
from ..metrics import MetricsRegistry
from ..rescache.keys import cache_bypass_requested, request_key
from ..rollout.drain import (DRAINING_HEADER, DrainingError, DrainState,
                             drain_worker)
from ..rollout.canary import generation_label
from ..service import APIService
from ..service.task_manager import TaskManagerBase
from ..taskstore import TaskStatus
from .batcher import BatcherSaturated, MicroBatcher
from .mesh.redelivery import RowPoisoned, redeliver_poisoned
from .registry import ModelRuntime, ServableModel

log = logging.getLogger("ai4e_tpu.worker")


class InferenceWorker:
    """Hosts one or more servables behind one service shell."""

    def __init__(self, name: str, runtime: ModelRuntime, batcher: MicroBatcher,
                 task_manager: TaskManagerBase | None = None,
                 prefix: str = "v1", metrics: MetricsRegistry | None = None,
                 store=None, reporter=None, result_cache=None,
                 checkpoint_root: str | None = None,
                 admin_api_keys=None, cache_sync_path: bool = True,
                 hop_ledger: bool = False,
                 drain_timeout_s: float = 30.0):
        import os

        self.runtime = runtime
        self.batcher = batcher
        self.store = store
        # Hop-ledger participation (observability/ledger.py,
        # AI4E_OBSERVABILITY_HOP_LEDGER): each async request carries a
        # HopLedger buffer through the batcher (batch cut + device
        # phases) and flushes it to the task store in ONE call before
        # the terminal transition — so the control plane's per-task
        # timeline is complete across the process boundary. Off (the
        # default) allocates nothing and makes no extra store calls.
        self._hop_ledger = hop_ledger
        # Inference result cache (rescache/): the sync path answers repeat
        # requests from it (keyed on model + params_version + wire + body,
        # so a reload's version bump alone already misses), and a checkpoint
        # hot reload invalidates every family this worker serves — a stale
        # result can never outlive a weight swap.
        self.result_cache = result_cache
        # False when a CACHING GATEWAY fronts this worker with the same
        # ResultCache (combined-process assembly, bench): the proxy already
        # answers hits and fills on response — a second worker-keyed entry
        # per request would hold identical bytes twice against the byte
        # budget and could never be hit by gateway traffic (the gateway
        # answers from its own key first). The reload invalidation hook is
        # unaffected — it needs only the cache reference.
        self._cache_sync_path = cache_sync_path
        # Hot-reload confinement (ADVICE r5): when set, reload checkpoints
        # must resolve (realpath, symlinks included) under this directory —
        # anything else answers 403. None preserves the open single-host
        # behavior for dev/tests.
        self._checkpoint_root = (os.path.realpath(checkpoint_root)
                                 if checkpoint_root else None)
        # API-key gate for the admin surface (reload): the same subscription
        # keys the gateway's middleware checks; None → open.
        self._admin_keys = set(admin_api_keys) if admin_api_keys else None
        self.service = APIService(name, prefix=prefix,
                                  task_manager=task_manager, metrics=metrics,
                                  reporter=reporter)
        # Deadline drops at the worker's submit hop (admission/): the same
        # series the gateway/dispatcher/batcher report into.
        self._expired_total = self.service.metrics.counter(
            "ai4e_admission_expired_total",
            "Requests dropped on deadline expiry, by hop/priority")
        self._served: dict[str, dict] = {}  # model -> endpoint listing
        # Streaming decode engines served via serve_stream — the reload
        # endpoint resolves LM names here (they never enter
        # runtime.models) and the launcher starts/stops them.
        self.decode_engines: list = []
        # Serializes hot reloads: concurrent swaps would otherwise leave
        # checkpoint_path/params_version reporting a different rollout
        # than the params actually serving.
        self._reload_lock = asyncio.Lock()
        # Rollout drain (rollout/drain.py, AI4E_ROLLOUT_DRAIN_TIMEOUT_MS):
        # one state machine shared by every surface of this process — the
        # batcher, the decode engines, the reload verb and the admission
        # checks all consult it.
        self.drain_state = DrainState()
        self._drain_timeout_s = drain_timeout_s
        # Per-generation serving outcomes/latency (docs/METRICS.md): the
        # rollout controller's burn guard compares these series between
        # the canary and the incumbent generation. The label is bounded
        # by generation_label (AIL013 — top-N+other).
        self._rollout_outcomes = self.service.metrics.counter(
            "ai4e_rollout_outcomes_total",
            "Worker inference outcomes by rollout generation")
        self._rollout_latency = self.service.metrics.histogram(
            "ai4e_rollout_request_seconds",
            "Worker inference latency by rollout generation")
        self._drain_gauge = self.service.metrics.gauge(
            "ai4e_rollout_drain_state",
            "Worker drain state (0 active, 1 draining, 2 drained)")
        self.service.app.router.add_get(self.service.prefix + "/models",
                                        self._list_models)
        self.service.app.router.add_post(
            self.service.prefix + "/models/{name}/reload",
            self._reload_model)
        self.service.app.router.add_post(
            self.service.prefix + "/worker/drain", self._drain_worker)
        self.service.app.router.add_get(
            self.service.prefix + "/worker/drain", self._drain_status)
        self.service.app.router.add_post(
            self.service.prefix + "/worker/resume", self._resume_worker)

    def _admin_denied(self, request):
        """The admin surface's API-key gate (reload/drain/resume): same
        header contract as the gateway's middleware; None passes."""
        if self._admin_keys is None:
            return None
        from aiohttp import web
        key = (request.headers.get("Ocp-Apim-Subscription-Key")
               or request.headers.get("X-Api-Key"))
        if key not in self._admin_keys:
            return web.json_response(
                {"error": "missing or invalid subscription key"},
                status=401)
        return None

    async def _drain_worker(self, request):
        """POST {prefix}/worker/drain — graceful drain: stop admitting,
        retire uncut work (each async task redelivers through the broker),
        finish in-flight device batches / active decode sequences bounded
        by the drain budget, then force-retire stragglers. Idempotent —
        a second POST reports the current state. Body (optional):
        ``{"timeout_ms": N}`` overrides the configured budget."""
        from aiohttp import web
        denied = self._admin_denied(request)
        if denied is not None:
            return denied
        timeout_s = self._drain_timeout_s
        try:
            payload = json.loads(await request.read() or b"{}")
            if isinstance(payload, dict) and "timeout_ms" in payload:
                timeout_s = max(0.0, float(payload["timeout_ms"])) / 1000.0
        except (json.JSONDecodeError, TypeError, ValueError):
            return web.json_response({"error": "invalid JSON"}, status=400)
        summary = await drain_worker(
            self.drain_state, batchers=[self.batcher],
            engines=self.decode_engines, timeout_s=timeout_s)
        self._drain_gauge.set(self.drain_state.state_code)
        log.warning("worker drained: %s", summary)
        return web.json_response(summary)

    async def _drain_status(self, _request):
        from aiohttp import web
        return web.json_response({
            "state": self.drain_state.state,
            "reloads_in_flight": self.drain_state.reloads_in_flight,
            "batcher_pending": self.batcher.pending_count,
            "decode_active": sum(e.active_count
                                 for e in self.decode_engines)})

    async def _resume_worker(self, request):
        """POST {prefix}/worker/resume — re-arm after an aborted drain:
        the rollback path re-weights this replica back into service
        without a process restart."""
        from aiohttp import web
        denied = self._admin_denied(request)
        if denied is not None:
            return denied
        self.drain_state.resume()
        self.batcher.resume_from_drain()
        for engine in self.decode_engines:
            engine.resume_from_drain()
        self._drain_gauge.set(self.drain_state.state_code)
        log.warning("worker resumed from drain")
        return web.json_response({"state": self.drain_state.state})

    async def _list_models(self, _request):
        """Model-registry introspection — what the reference delegates to its
        container registry + values files, queryable live here."""
        from aiohttp import web
        out = []
        # Mesh serving plane: the validated layout + live health, one per
        # endpoint (worker-level, every model on it) — how clients and
        # the orchestrator discover the shape/cost tier a worker serves
        # (docs/mesh_serving.md#introspection).
        mesh_desc = (self.runtime.describe()
                     if hasattr(self.runtime, "layout")
                     and hasattr(self.runtime, "describe") else None)
        for name, s in self.runtime.models.items():
            entry = {
                "name": name, "version": s.version,
                "params_version": s.params_version,
                "generation": s.generation,
                "checkpoint": s.checkpoint_path,
                "input_shape": list(s.input_shape),
                "input_dtype": str(np.dtype(s.input_dtype)),
                "batch_buckets": list(s.batch_buckets),
                "endpoints": self._served.get(name, {}),
            }
            if mesh_desc is not None:
                entry["mesh"] = mesh_desc
            if s.stack_item_shape is not None:
                # The batch-STACK contract when it differs from the device
                # input shape (wire-encoded servables): clients discover the
                # shape stacks must ship in, not the on-device layout.
                entry["stack_item_shape"] = list(s.stack_item_shape)
                entry["stack_item_dtype"] = str(np.dtype(
                    s.stack_item_dtype if s.stack_item_dtype is not None
                    else s.input_dtype))
            out.append(entry)
        return web.json_response({"models": out})

    async def _reload_model(self, request):
        """POST {prefix}/models/{name}/reload — hot-swap the model's weights
        from its checkpoint (or a new one in the JSON body), no restart, no
        recompile (``ModelRuntime.reload_params``). The reference updates a
        model by building + rolling a new container image; here a retrained
        checkpoint lands on the shared mount and this endpoint flips serving
        to it between batches.

        Body (optional): ``{"checkpoint": "/abs/or/relative/path"}`` —
        relative paths resolve against the model's current checkpoint
        directory. Errors: 404 unknown model, 400 no checkpoint known,
        409 checkpoint tree mismatch, 501 on a multi-host slice (every
        process would need the swap; roll replicas there instead)."""
        import os

        from aiohttp import web

        import jax

        denied = self._admin_denied(request)
        if denied is not None:
            return denied
        name = request.match_info["name"]
        servable = self.runtime.models.get(name)
        lm_backend = None
        if servable is None:
            # Streaming LMs live on decode engines, not runtime.models;
            # their reload additionally invalidates the pooled KV cache
            # (params_version bump → the engine re-prefills actives,
            # docs/streaming.md).
            lm_backend = next(
                (e.backend for e in self.decode_engines
                 if getattr(e.backend, "name", None) == name), None)
            if lm_backend is None:
                return web.json_response({"error": "unknown model"},
                                         status=404)
            servable = lm_backend.servable
        if jax.process_count() > 1:
            return web.json_response(
                {"error": "hot reload is single-host; drain each replica "
                          "(POST /v1/worker/drain) and roll the multi-host "
                          "slice through the rollout controller instead "
                          "(docs/deployment.md#rollouts)"}, status=501)
        try:
            payload = json.loads(await request.read() or b"{}")
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        if not isinstance(payload, dict):
            return web.json_response(
                {"error": "body must be a JSON object"}, status=400)
        path = payload.get("checkpoint") or servable.checkpoint_path
        if not path:
            return web.json_response(
                {"error": "model has no checkpoint to reload; pass "
                          '{"checkpoint": ...}'}, status=400)
        if not isinstance(path, str):
            return web.json_response(
                {"error": "checkpoint must be a string path"}, status=400)
        if not os.path.isabs(path):
            if not servable.checkpoint_path:
                # No directory to resolve against — orbax would resolve
                # it against the server CWD, a silent wrong place.
                return web.json_response(
                    {"error": "relative checkpoint path but the model has "
                              "no recorded checkpoint directory; pass an "
                              "absolute path"}, status=400)
            path = os.path.abspath(os.path.join(
                os.path.dirname(servable.checkpoint_path), path))
        if self._checkpoint_root is not None:
            # Realpath-prefix confinement (ADVICE r5): the request body names
            # a filesystem path — without this check anyone who can reach
            # the worker port could swap the served weights to ANY readable
            # checkpoint on disk ("../" traversal, absolute paths, symlink
            # hops included).
            real = os.path.realpath(path)
            if not (real == self._checkpoint_root
                    or real.startswith(self._checkpoint_root + os.sep)):
                return web.json_response(
                    {"error": "checkpoint path escapes the configured "
                              "checkpoint directory"}, status=403)
            path = real

        generation = payload.get("generation")
        if generation is not None and not isinstance(generation, int):
            return web.json_response(
                {"error": "generation must be an integer"}, status=400)

        def load_and_swap():
            from ..checkpoint import load_params
            new_params = load_params(path, like=servable.params)
            if lm_backend is not None:
                lm_backend.reload_params(new_params)
                return servable
            return self.runtime.reload_params(name, new_params)

        # Drain interlock (rollout/drain.py): check + register are one
        # synchronous step, so a reload racing a drain either lands fully
        # before the drain (which then waits on reloads_in_flight) or is
        # refused here — a weight swap can never complete on a worker
        # that already reported itself drained
        # (tests/test_race_regressions.py).
        if not self.drain_state.try_begin_reload():
            return web.json_response(
                {"error": "worker is draining; reload refused — the "
                          "rollout path owns this replica now"},
                status=409, headers={DRAINING_HEADER: "1"})
        try:
            async with self._reload_lock:
                try:
                    # Off the event loop: orbax reads disk and device_puts.
                    await asyncio.to_thread(load_and_swap)
                except ValueError as exc:
                    return web.json_response({"error": str(exc)}, status=409)
                except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the error is returned to the caller as the 400 body
                    return web.json_response(
                        {"error": f"reload failed: {type(exc).__name__}: "
                                  f"{exc}"}, status=400)
                servable.checkpoint_path = path
                if generation is not None:
                    # The rollout coordinate: the controller's reload
                    # carries the target generation; the canary split
                    # routes on it (rollout/canary.py).
                    servable.generation = generation
                if self.result_cache is not None:
                    # Invalidation-on-reload (rescache/): drop every cached
                    # result this model could have produced — the worker's
                    # own family (sync path) AND each endpoint path it
                    # serves (the gateway/dispatcher key namespace) — so a
                    # result computed on the old weights is unreachable
                    # from the moment the swap lands.
                    for family in (name,
                                   *self._served.get(name, {}).values()):
                        self.result_cache.invalidate_family(family)
                return web.json_response(
                    {"model": name, "checkpoint": path,
                     "params_version": servable.params_version,
                     "generation": servable.generation})
        finally:
            self.drain_state.end_reload()

    def serve_model(self, servable: ServableModel,
                    sync_path: str | None = None,
                    async_path: str | None = None,
                    maximum_concurrent_requests: int = 64,
                    pipeline_to=None) -> None:
        """Expose a servable on sync + async endpoints.

        ``pipeline_to`` makes this servable a *pipeline stage* (the composite
        ensembles of ``distributed_api_task.py:67-100``): a callable
        ``(result) -> (next_endpoint, body_bytes) | None`` evaluated after
        inference on the async path. A two-argument callable additionally
        receives the stage's decoded input example — payload-shaping
        handoffs (``handoffs.crops_handoff`` shipping detector crops to the
        classifier) need the image, not just the JSON result. A tuple hands
        the task — same TaskId — to the next API via AddPipelineTask;
        ``None`` means "nothing to hand off" and the stage completes the
        task itself (e.g. a detector that found no animals skips the
        classifier).
        """
        if pipeline_to is not None:
            params = [
                p for p in inspect.signature(pipeline_to).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            handoff_wants_example = len(params) >= 2
        else:
            handoff_wants_example = False
        name = servable.name
        sync_path = sync_path or f"/{name}"
        async_path = async_path or f"/{name}-async"
        self._served.setdefault(name, {}).update({
            "sync": self.service.prefix + sync_path,
            "async": self.service.prefix + async_path})

        def _saturation_check():
            # Drain gate first (rollout/drain.py): a draining worker
            # refuses BEFORE adopting a task — the broker redelivers it to
            # a peer, and the X-Draining marker ejects this backend from
            # placement for a TTL instead of tripping a breaker.
            if self.drain_state.is_draining:
                return (503, "Worker draining; retry a peer.",
                        {"Retry-After": "1", DRAINING_HEADER: "1",
                         SHED_REASON_HEADER:
                             shed_reason("worker", "draining")})
            # Mesh-endpoint health gate (docs/mesh_serving.md): a dead
            # follower means THIS endpoint cannot answer correctly — 500,
            # a breaker FAILURE, so dispatchers eject it and route to
            # healthy replicas. Deliberately not 503: observe_status
            # treats 503 as saturation-neutral ("peers are melting too"),
            # which must not apply to a half-dead mesh.
            health = getattr(self.runtime, "health", None)
            if health is not None and not health.healthy:
                return 500, f"Mesh endpoint unhealthy: {health.reason}"
            # Admission-time backpressure: refuse BEFORE adopting a task so
            # the dispatcher's 503 handling (delay + redeliver) engages —
            # queue-depth-vs-device-occupancy replacing the reference's
            # per-replica thread cap (SURVEY.md §7 hard part #2).
            if self.batcher.pending_count >= self.batcher.max_pending:
                return 503, "Inference queue saturated; retry later.", {
                    "Retry-After": "1"}
            return None

        async def _sync_request_kwargs(request):
            # Default body/content_type extraction plus the cache opt-out:
            # the handler signature has no request object, and the
            # documented X-Cache-Bypass / Cache-Control: no-cache contract
            # ("this request must execute; no cache read, no store") must
            # hold at the worker's own cache too — the gateway's sync proxy
            # forwards these headers verbatim. Admission state rides the
            # same extraction: X-Deadline-At (stamped by the proxy) or
            # X-Deadline-Ms (a direct caller), X-Priority.
            return {"body": await request.read(),
                    "content_type": request.content_type,
                    "cache_bypass": cache_bypass_requested(request.headers),
                    **worker_admission_kwargs(request.headers)}

        async def _async_request_kwargs(request):
            # The dispatcher forwards X-Deadline-At / X-Priority on its
            # backend POST (broker/dispatcher.py); the worker is the LAST
            # shed point before the device, so the handler needs them.
            return {"body": await request.read(),
                    "content_type": request.content_type,
                    **worker_admission_kwargs(request.headers)}

        @self.service.api_sync_func(
            sync_path, maximum_concurrent_requests=maximum_concurrent_requests,
            admission_check=_saturation_check,
            request_processing_function=_sync_request_kwargs)
        async def _sync(body, content_type, cache_bypass=False,
                        deadline_at=0.0, priority=0, _name=name,
                        _servable=servable):
            if expired(deadline_at):
                # Submit-hop shed (admission/): the budget is already gone —
                # answering 504 now is strictly better than computing a
                # result the caller stopped waiting for.
                self._expired_total.inc(hop="worker",
                                        priority=priority_name(priority))
                from aiohttp import web
                return web.Response(
                    status=504, text="Deadline exceeded before execution.",
                    headers={SHED_REASON_HEADER:
                             shed_reason("worker", "deadline")})
            # Worker-level result cache (rescache/): keyed on the model AND
            # its params_version, so a hot reload's version bump alone makes
            # every pre-swap entry unreachable (the reload hook additionally
            # invalidates the family outright).
            cache = (self.result_cache
                     if self._cache_sync_path and not cache_bypass else None)
            key = None
            if cache is not None:
                key = request_key(_name, body, content_type,
                                  checkpoint=str(_servable.params_version))
                # count=False: hit/miss outcomes are counted once, at the
                # gateway edge — this inner lookup must not double-count a
                # request the sync proxy already recorded.
                found = cache.get(key, count=False)
                if found is not None:
                    return json.loads(found[0])
            example = _servable.preprocess(body, content_type)
            gen_label = generation_label(_servable.generation)
            t0 = time.perf_counter()
            try:
                result = await self.batcher.submit(_name, np.asarray(example),
                                                   priority=priority,
                                                   deadline_at=deadline_at)
            except BatcherSaturated:
                from aiohttp import web
                self._rollout_outcomes.inc(generation=gen_label,
                                           outcome="saturated")
                return web.Response(status=503,
                                    text="Inference queue saturated; retry.",
                                    headers={"Retry-After": "1"})
            except DrainingError:
                # Raced the drain flip between admission and submit: the
                # refusal is retryable at a peer, never a failure of this
                # request (docs/deployment.md#drain).
                from aiohttp import web
                self._rollout_outcomes.inc(generation=gen_label,
                                           outcome="draining")
                return web.Response(
                    status=503, text="Worker draining; retry a peer.",
                    headers={"Retry-After": "1", DRAINING_HEADER: "1"})
            except RowPoisoned:
                # Sync path has no task to redeliver — answer an honest
                # retryable error (503: the caller/proxy retries; other
                # rows of the batch were unaffected), never the zeros-
                # shard "result".
                from aiohttp import web
                return web.Response(
                    status=503,
                    text="Result invalidated by a degraded mesh host; retry.",
                    headers={"Retry-After": "1"})
            except DeadlineExceeded as exc:
                from aiohttp import web
                self._rollout_outcomes.inc(generation=gen_label, outcome="expired")
                return web.Response(
                    status=504, text="Deadline exceeded while queued.",
                    headers={SHED_REASON_HEADER:
                             shed_reason(exc.hop, "deadline")})
            except Exception:
                self._rollout_outcomes.inc(generation=gen_label, outcome="error")
                raise
            self._rollout_outcomes.inc(generation=gen_label, outcome="ok")
            self._rollout_latency.observe(time.perf_counter() - t0,
                                          generation=gen_label)
            out = _jsonable(result)
            if key is not None:
                cache.put(key, json.dumps(out).encode(), "application/json")
            return out

        @self.service.api_async_func(
            async_path, maximum_concurrent_requests=maximum_concurrent_requests,
            admission_check=_saturation_check,
            request_processing_function=_async_request_kwargs)
        async def _async(taskId, body, content_type, deadline_at=0.0,
                         priority=0, _name=name, _servable=servable):
            tm = self.service.task_manager
            buf = None
            if self._hop_ledger:
                from ..observability.ledger import HopLedger
                buf = HopLedger()
            if expired(deadline_at):
                # Submit-hop shed (admission/): terminal `expired`, never
                # adopted into the batcher — the dispatcher treats the 200
                # as delivered and the store transition carries provenance.
                self._expired_total.inc(hop="worker",
                                        priority=priority_name(priority))
                await tm.update_task_status(
                    taskId, expired_status("worker"), TaskStatus.EXPIRED)
                return
            await tm.update_task_status(taskId, f"running - {_name} inference")
            try:
                example = _servable.preprocess(body, content_type)
            except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the error is recorded on the task record (failed - bad input)
                await tm.fail_task(taskId, f"failed - bad input: {exc}")
                return
            gen_label = generation_label(_servable.generation)
            t0 = time.perf_counter()
            try:
                result = await self.batcher.submit(_name, np.asarray(example),
                                                   priority=priority,
                                                   deadline_at=deadline_at,
                                                   ledger=buf)
            except BatcherSaturated:
                # Saturated between admission and submit: hand the task back
                # to the broker (same-endpoint republish with empty body →
                # original-body replay → redelivery) instead of failing it.
                self._rollout_outcomes.inc(generation=gen_label,
                                           outcome="saturated")
                current = await tm.get_task_status(taskId)
                endpoint = (current or {}).get("Endpoint", async_path)
                await tm.add_pipeline_task(taskId, endpoint)
                return
            except DrainingError:
                # The drain retired this entry before it was cut to the
                # device (or the flip raced submit): redeliver the task
                # through the broker — per task, exactly the poisoned-row
                # path — so a peer serves it and no client sees a loss
                # (docs/deployment.md#drain).
                self._rollout_outcomes.inc(generation=gen_label,
                                           outcome="draining")
                if buf is not None:
                    from ..observability.ledger import RETRY
                    buf.stamp(RETRY, "worker", reason="draining")
                await self._flush_ledger(tm, taskId, buf)
                await redeliver_poisoned(tm, taskId, async_path)
                return
            except RowPoisoned:
                # A degraded mesh host invalidated THIS row (the batch's
                # other rows completed): redeliver the task through the
                # broker — per-task retry, never a terminal failure and
                # never a silent wrong answer. The redelivery helper
                # probes terminality first, so a concurrently completed
                # duplicate is suppressed, not re-executed
                # (docs/mesh_serving.md#poisoned-rows).
                if buf is not None:
                    from ..observability.ledger import RETRY
                    buf.stamp(RETRY, "worker", reason="poisoned-row")
                await self._flush_ledger(tm, taskId, buf)
                await redeliver_poisoned(tm, taskId, async_path)
                return
            except DeadlineExceeded as exc:
                # Expired while pending in the batcher (which already
                # counted the hop metric): terminal transition only.
                self._rollout_outcomes.inc(generation=gen_label, outcome="expired")
                await self._flush_ledger(tm, taskId, buf)
                await tm.update_task_status(
                    taskId, expired_status(exc.hop), TaskStatus.EXPIRED)
                return
            except Exception:
                # Execution failure (device error surfacing through the
                # batch future): the service shell fails the task AFTER
                # this re-raise — flush the batched/phase stamps FIRST,
                # while the task is still non-terminal, so exactly the
                # failed requests the flight recorder keeps at 100 %
                # carry their worker-side timeline.
                self._rollout_outcomes.inc(generation=gen_label, outcome="error")
                await self._flush_ledger(tm, taskId, buf)
                raise
            self._rollout_outcomes.inc(generation=gen_label, outcome="ok")
            self._rollout_latency.observe(time.perf_counter() - t0,
                                          generation=gen_label)
            if pipeline_to is not None:
                if handoff_wants_example:
                    # Handoffs consume the natural image; wire-encoded
                    # servables (yuv420 flat planes) decode it back first.
                    img = (_servable.example_decoder(example)
                           if _servable.example_decoder is not None
                           else example)
                    handoff = pipeline_to(result, img)
                else:
                    handoff = pipeline_to(result)
                if handoff is not None:
                    next_endpoint, next_body = handoff
                    # Stage 1's device phases flush now — the next
                    # stage's worker opens its own buffer under the
                    # same TaskId, so the timeline spans the pipeline.
                    await self._flush_ledger(tm, taskId, buf)
                    # Keep the stage's intermediate output retrievable
                    # under the same TaskId while the task moves on.
                    await self._store_result(
                        taskId, json.dumps(_jsonable(result)).encode(),
                        stage=_name)
                    await tm.update_task_status(
                        taskId, f"running - {_name} handing off to "
                                f"{next_endpoint}")
                    await tm.add_pipeline_task(taskId, next_endpoint,
                                               body=next_body)
                    return
            # Flush BEFORE the result write and the terminal transition:
            # the task is still live (retention cannot have evicted it),
            # and a failing result hop then still leaves the timeline on
            # the record for the shell's failure path.
            await self._flush_ledger(tm, taskId, buf)
            await self._store_result(
                taskId, json.dumps(_jsonable(result)).encode())
            await tm.complete_task(
                taskId, f"completed - {_summarise(result)}")

    async def _flush_ledger(self, tm, task_id: str, buf) -> None:
        """Ship a request's buffered hop-ledger events to the store in
        one call; DRAINS the buffer, so the finally backstop after an
        already-flushed path is a no-op. Failures are dropped with a
        debug log — fail-open telemetry, never a serving error
        (docs/observability.md)."""
        if buf is None:
            return
        events = buf.drain()
        if not events:
            return
        try:
            await tm.append_ledger(task_id, events)
        except Exception:  # noqa: BLE001 — observability is fail-open: a dropped flush loses a timeline, not a task
            log.debug("hop-ledger flush dropped for task %s", task_id,
                      exc_info=True)

    def serve_stream(self, engine, async_path: str | None = None,
                     maximum_concurrent_requests: int = 64,
                     event_hub=None) -> None:
        """Expose a streaming autoregressive endpoint over a
        ``DecodeEngine`` (``runtime/decode.py``) — the continuous-
        batching serving path. The request joins the running decode
        batch between steps; every generated token is published as a
        ``chunk`` event through ``event_hub`` (the PR 9 ``TaskEventHub``)
        under the request's TaskId, so ``GET /v1/taskmanagement/task/
        {id}/events`` streams tokens live while the task runs.

        Request body (JSON): ``{"prompt": [token ids],
        "max_new_tokens": N}``. The stored result is
        ``{"tokens": [...], "count": N}``. ``event_hub=None`` (a worker
        process with no in-process hub) still serves — tokens just
        aren't fanned out as SSE chunks from THIS process.

        Backpressure rides the existing admission path: a saturated
        engine answers 503 at admission (the dispatcher's delay +
        redeliver contract), and a mid-handler saturation republishes
        the task exactly like the batch path.
        """
        from ..pipeline.events import CHUNK
        from .decode import DecodeSaturated

        name = engine.backend.name
        async_path = async_path or f"/{name}-stream-async"
        self._served.setdefault(name, {}).update(
            stream_async=self.service.prefix + async_path)
        self.decode_engines.append(engine)
        vocab = getattr(engine.backend, "servable", None)
        vocab = getattr(vocab, "vocab_size", None)

        def _saturation_check():
            if self.drain_state.is_draining:
                return (503, "Worker draining; retry a peer.",
                        {"Retry-After": "1", DRAINING_HEADER: "1",
                         SHED_REASON_HEADER:
                             shed_reason("worker", "draining")})
            if engine.pending_count >= engine.max_pending:
                return 503, "Decode queue saturated; retry later.", {
                    "Retry-After": "1"}
            return None

        async def _request_kwargs(request):
            return {"body": await request.read(),
                    "content_type": request.content_type,
                    **worker_admission_kwargs(request.headers)}

        def _parse(body: bytes) -> tuple[list[int], int]:
            payload = json.loads(body)
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            # "prompt" is the client wire; "tokens" lets an upstream
            # stage's stored result ({"tokens": [...]}) feed this stage
            # directly — the chained ASR→summarize pipeline shape
            # (docs/streaming.md).
            prompt = payload.get("prompt", payload.get("tokens"))
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int) for t in prompt)):
                raise ValueError('"prompt" must be a non-empty list of '
                                 'token ids')
            if vocab is not None and any(
                    not 0 <= t < vocab for t in prompt):
                raise ValueError(f"token ids must be in [0, {vocab})")
            if len(prompt) >= engine.backend.max_len:
                # Client-input error, failed HERE so it lands as
                # "failed - bad input" like every other bad payload —
                # engine.submit's own guard would otherwise surface
                # through the shell's crash path.
                raise ValueError(
                    f"prompt of {len(prompt)} tokens leaves no room to "
                    f"generate under the KV-cache length "
                    f"{engine.backend.max_len}")
            max_new = payload.get("max_new_tokens", 64)
            if not isinstance(max_new, int) or max_new < 1:
                raise ValueError('"max_new_tokens" must be a positive int')
            return prompt, max_new

        @self.service.api_async_func(
            async_path,
            maximum_concurrent_requests=maximum_concurrent_requests,
            admission_check=_saturation_check,
            request_processing_function=_request_kwargs)
        async def _stream(taskId, body, content_type, deadline_at=0.0,
                          priority=0, _name=name):
            tm = self.service.task_manager
            buf = None
            if self._hop_ledger:
                from ..observability.ledger import HopLedger
                buf = HopLedger()
            if expired(deadline_at):
                self._expired_total.inc(hop="worker",
                                        priority=priority_name(priority))
                await tm.update_task_status(
                    taskId, expired_status("worker"), TaskStatus.EXPIRED)
                return
            try:
                prompt, max_new = _parse(body)
            except (ValueError, json.JSONDecodeError) as exc:
                await tm.fail_task(taskId, f"failed - bad input: {exc}")
                return
            # Pipeline-stage chunk layering (docs/pipelines.md): a stage
            # sub-task's tokens publish under the ROOT TaskId — the one
            # stream a client watches — with the stage name labeling
            # which node is talking, exactly like the coordinator's
            # `stage` events.
            publish_id = taskId
            if event_hub is not None:
                from ..pipeline.spec import split_sub_task_id
                root = split_sub_task_id(taskId)
                if root is not None:
                    publish_id = root[0]
                # Buffer chunks even before any SSE subscriber attaches —
                # a client connecting mid-stream replays the (bounded)
                # token history (docs/streaming.md).
                event_hub.track(publish_id)
            await tm.update_task_status(taskId, f"running - {_name} decode")

            def on_token(index: int, token: int) -> None:
                if event_hub is not None:
                    event_hub.publish(publish_id, CHUNK,
                                      {"stage": _name, "index": index,
                                       "data": {"token": token}})

            try:
                tokens = await engine.submit(prompt, max_new,
                                             on_token=on_token,
                                             priority=priority,
                                             deadline_at=deadline_at,
                                             ledger=buf)
            except DecodeSaturated:
                # Saturated between admission and submit: hand the task
                # back to the broker, same as the batch path.
                current = await tm.get_task_status(taskId)
                endpoint = (current or {}).get("Endpoint", async_path)
                await tm.add_pipeline_task(taskId, endpoint)
                return
            except DrainingError:
                # Drained mid-decode (queued entry retired, or an active
                # straggler force-retired past the budget): redeliver
                # through the broker per task — a peer re-decodes from
                # the prompt, the client never sees the drain.
                if buf is not None:
                    from ..observability.ledger import RETRY
                    buf.stamp(RETRY, "worker", reason="draining")
                await self._flush_ledger(tm, taskId, buf)
                await redeliver_poisoned(tm, taskId, async_path)
                return
            except DeadlineExceeded as exc:
                await self._flush_ledger(tm, taskId, buf)
                await tm.update_task_status(
                    taskId, expired_status(exc.hop), TaskStatus.EXPIRED)
                return
            except Exception:
                await self._flush_ledger(tm, taskId, buf)
                raise
            await self._flush_ledger(tm, taskId, buf)
            await self._store_result(taskId, json.dumps(
                {"tokens": tokens, "count": len(tokens)}).encode())
            await tm.complete_task(
                taskId, f"completed - {len(tokens)} tokens")

    def serve_batch(self, servable: ServableModel,
                    sync_path: str | None = None,
                    async_path: str | None = None,
                    max_items: int = 1024,
                    submit_concurrency: int = 64,
                    progress_every: float = 2.0,
                    maximum_concurrent_requests: int = 8) -> None:
        """Expose a *batch* API for a servable: one request carries a stack of
        N examples (npy array of shape ``(N, *stack_item_shape)`` — which is
        ``input_shape`` unless the servable declares a wire adapter, e.g.
        yuv420 servables take ``(N, H, W, 3)`` stacks and convert each item
        at ingestion), the platform fans them into the micro-batcher and
        aggregates the results.

        The reference's batch APIs (``APIs/Projects/camera-trap/
        batch-detection-async.dockerfile``) are long-running tasks over many
        images inside one container; here the stack rides the same device
        batching as everything else — a 1000-image batch task and single-image
        requests interleave on the mesh. Per-image failure isolation: a bad
        image yields an ``error`` entry at its index, never failing the stack
        (SURVEY.md §7 hard part #1). The async path reports incremental
        progress ("running - k/N"), the reference's long-task status contract
        (``ai4e_service.py:180-213``).
        """
        import asyncio
        import io

        name = servable.name
        sync_path = sync_path or f"/{name}-batch"
        async_path = async_path or f"/{name}-batch-async"
        self._served.setdefault(name, {}).update(
            batch_sync=self.service.prefix + sync_path,
            batch_async=self.service.prefix + async_path)
        # Stacks arrive in the servable's natural payload shape; servables
        # whose device input differs (yuv420's flat planes) declare the
        # stack shape + a per-item adapter, so batch clients and the crops
        # handoff keep shipping plain (N, H, W, 3) arrays on every wire.
        item_shape = tuple(servable.stack_item_shape
                           or servable.input_shape)
        item_dtype = (servable.stack_item_dtype
                      if servable.stack_item_dtype is not None
                      else servable.input_dtype)

        def _decode_stack(body: bytes) -> np.ndarray:
            arr = np.load(io.BytesIO(body))
            if arr.ndim != len(item_shape) + 1 or tuple(arr.shape[1:]) != item_shape:
                raise ValueError(
                    f"expected stack (N, {', '.join(map(str, item_shape))}), "
                    f"got {arr.shape}")
            if len(arr) == 0:
                raise ValueError("empty batch")
            if len(arr) > max_items:
                raise ValueError(f"batch of {len(arr)} exceeds max {max_items}")
            if servable.stack_validator is not None:
                # Raw-value validation BEFORE the cast (see ServableModel).
                servable.stack_validator(arr)
            from .families import cast_image_payload
            arr = cast_image_payload(arr, item_dtype)
            if servable.stack_adapter is not None:
                arr = np.stack([servable.stack_adapter(x) for x in arr])
            return arr

        async def _run_stack(stack: np.ndarray, on_progress=None) -> list:
            results: list = [None] * len(stack)
            done = 0
            queue: asyncio.Queue[int] = asyncio.Queue()
            for i in range(len(stack)):
                queue.put_nowait(i)

            async def _puller():
                nonlocal done
                while True:
                    try:
                        i = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        return
                    while True:
                        try:
                            # Background priority: the stack shares device
                            # batches with interactive traffic but never
                            # queues ahead of it.
                            out = await self.batcher.submit(
                                name, np.asarray(stack[i]), priority=1)
                            results[i] = {"index": i, "result": _jsonable(out)}
                            break
                        except BatcherSaturated:
                            # Throttle, don't fail: the stack shares the
                            # device with interactive traffic.
                            await asyncio.sleep(0.05)
                        except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the error is reported in the batch result payload for this index
                            results[i] = {"index": i, "error": str(exc)}
                            break
                    done += 1
                    if on_progress is not None:
                        await on_progress(done, len(stack))

            pullers = min(submit_concurrency, len(stack))
            await asyncio.gather(*(_puller() for _ in range(pullers)))
            return results

        @self.service.api_sync_func(
            sync_path, maximum_concurrent_requests=maximum_concurrent_requests)
        async def _sync_batch(body, content_type):
            # Off the event loop: decoding + per-item wire conversion of a
            # 1000-image stack is seconds of numpy work that must not stall
            # the interactive traffic the priority classes protect.
            stack = await asyncio.to_thread(_decode_stack, body)
            results = await _run_stack(stack)
            failed = sum(1 for r in results if "error" in r)
            return {"count": len(results), "failed": failed, "items": results}

        @self.service.api_async_func(
            async_path, maximum_concurrent_requests=maximum_concurrent_requests)
        async def _async_batch(taskId, body, content_type):
            tm = self.service.task_manager
            try:
                stack = await asyncio.to_thread(_decode_stack, body)
            except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the error is recorded on the task record (failed - bad input)
                await tm.fail_task(taskId, f"failed - bad input: {exc}")
                return
            total = len(stack)
            await tm.update_task_status(
                taskId, f"running - {name} batch 0/{total}")
            last = {"t": 0.0}

            async def on_progress(k, n):
                import time as _t
                now = _t.monotonic()
                if now - last["t"] >= progress_every or k == n:
                    last["t"] = now
                    await tm.update_task_status(
                        taskId, f"running - {name} batch {k}/{n}")

            results = await _run_stack(stack, on_progress)
            failed = sum(1 for r in results if "error" in r)
            await self._store_result(taskId, json.dumps(
                {"count": total, "failed": failed, "items": results}).encode())
            # Never put the word "failed" in this terminal status: canonical
            # bucketing (TaskStatus.canonical) and SDK wait() test "failed"
            # first, so "completed - N images, 0 failed" would land every
            # successful batch task in the failed set.
            await tm.complete_task(
                taskId, f"completed - {total} images, {failed} errors")

    async def _store_result(self, task_id: str, payload: bytes,
                            stage: str | None = None) -> None:
        """Works with both the in-process store (sync ``set_result``) and
        ``HttpResultStore`` (coroutine) — a remote worker stores results on
        the control plane's task store."""
        if self.store is None:
            return
        res = self.store.set_result(task_id, payload, stage=stage)
        if inspect.isawaitable(res):
            await res


def _jsonable(obj):
    import jax
    if isinstance(obj, (np.ndarray, jax.Array)):
        return np.asarray(obj).tolist()
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def _summarise(result) -> str:
    if isinstance(result, dict):
        return ", ".join(f"{k}" for k in result)
    if isinstance(result, list):
        return f"{len(result)} items"
    return str(result)[:64]
