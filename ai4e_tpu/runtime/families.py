"""Model-family factories — config-driven servable construction.

The reference publishes a model by baking it into a container image and
writing a Helm values file naming that image (``APIs/Charts/camera-trap/
detection-async/prod-values.yaml``). Here a *family* + kwargs in a worker
config produces a ready ``ServableModel``: the framework owns preprocess
(npy payload decoding), the jittable forward, and postprocess, so a
deployment file can say ``{"family": "unet", "tile": 256}`` and get the
land-cover API.

Families: ``echo`` (the base-py smoke API), ``unet`` (land-cover
segmentation), ``resnet`` (species classification), ``detector``
(camera-trap MegaDetector slot), ``vit`` (classification with
tensor-parallel sharding rules).
"""

from __future__ import annotations

import io

import jax
import numpy as np

from .ladder import DETECTOR_BUCKETS, IMAGE_BUCKETS
from .registry import ServableModel


def _finite_narrow_cast(arr: np.ndarray, dtype: np.dtype) -> np.ndarray:
    """Cast a float payload to a narrower float wire dtype, failing loudly:
    a bare astype maps |x| > dtype-max to inf, which would surface
    downstream as NaN scores instead of an error for this one task."""
    with np.errstate(over="ignore", invalid="ignore"):
        # The guard below is the error surface — the cast's own overflow
        # RuntimeWarning would pre-empt it under -W error and spam logs
        # otherwise.
        out = arr.astype(dtype, copy=False)
    if (np.issubdtype(dtype, np.floating)
            and np.issubdtype(arr.dtype, np.floating)
            and np.dtype(dtype).itemsize < arr.dtype.itemsize
            and not np.isfinite(out).all()):
        if np.isnan(arr).any():
            raise ValueError("payload contains NaN")
        raise ValueError(
            f"payload exceeds {np.dtype(dtype)} range (max |x| "
            f"{float(np.nanmax(np.abs(arr)))})")
    return out


def _npy_preprocess(shape: tuple, dtype=np.float32):
    dtype = np.dtype(dtype)

    def preprocess(body: bytes, content_type: str):
        arr = np.load(io.BytesIO(body))
        if arr.shape != shape:
            raise ValueError(f"expected {shape}, got {arr.shape}")
        return _finite_narrow_cast(arr, dtype)
    return preprocess


def _image_preprocess(shape: tuple, dtype=np.float32):
    """Payload decoder for (H, W, 3) models: ``image/*`` content types are
    decoded + resized with PIL (the reference's camera-trap APIs take camera
    JPEGs, e.g. ``APIs/Charts/camera-trap/detection-async``); anything else
    is treated as a raw npy array of the exact input shape. A broken image
    raises ValueError → fails that one task, never a batch."""
    h, w, _ = shape

    def preprocess(body: bytes, content_type: str):
        if content_type and content_type.startswith("image/"):
            try:
                from PIL import Image
            except ImportError as exc:  # pragma: no cover - PIL is baked in
                raise ValueError("image payloads need Pillow") from exc
            try:
                img = Image.open(io.BytesIO(body))
                img = img.convert("RGB").resize((w, h), Image.BILINEAR)
            except Exception as exc:  # noqa: BLE001 — bad image fails one task
                raise ValueError(f"undecodable image: {exc}") from exc
            arr = np.asarray(img, np.uint8)
            if np.dtype(dtype) == np.uint8:
                return arr
            # Float models get [0, 1] — the conventional image scaling.
            return arr.astype(np.float32) / 255.0
        arr = np.load(io.BytesIO(body))
        if arr.shape != shape:
            raise ValueError(f"expected {shape}, got {arr.shape}")
        return cast_image_payload(arr, dtype)

    return preprocess


def cast_image_payload(arr: np.ndarray, dtype) -> np.ndarray:
    """Cast a decoded payload to the servable's input dtype. Float [0,1]
    arrays headed for a uint8-ingesting model are SCALED, not truncated (a
    bare astype would zero the image); float→narrower-float goes through the
    finite-cast guard — shared by the single-request and batch-stack decode
    paths."""
    if np.dtype(dtype) == np.uint8 and arr.dtype != np.uint8:
        return np.clip(np.round(arr * 255.0), 0, 255).astype(np.uint8)
    return _finite_narrow_cast(arr, np.dtype(dtype))


def encode_classmap_png(classmap: np.ndarray) -> str:
    """(H, W) uint8 class ids → base64 PNG string (grayscale, lossless;
    pixel value == class id) — the classified-tile payload of the
    reference's land-cover API."""
    import base64

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(classmap.astype(np.uint8), mode="L").save(buf, "PNG")
    return base64.b64encode(buf.getvalue()).decode("ascii")


def _classification_postprocess(labels: list | None = None):
    """Softmax + argmax → {class_id, label?, confidence} — shared by every
    classifier family."""
    def postprocess(logits):
        logits = np.asarray(logits, np.float64)
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        top = int(np.argmax(probs))
        out = {"class_id": top, "confidence": float(probs[top])}
        if labels:
            out["label"] = labels[top]
        return out
    return postprocess


def build_echo(name: str = "echo", size: int = 16, buckets=(8,),
               **_) -> ServableModel:
    """Identity model — the reference's base-py echo API
    (``APIs/1.0/base-py/runserver.py`` role): proves the full transport
    without model weight."""
    import jax.numpy as jnp

    def apply_fn(params, batch):
        return jnp.asarray(batch) * params["scale"]

    return ServableModel(
        name=name, apply_fn=apply_fn, params={"scale": np.float32(1.0)},
        input_shape=(size,), preprocess=_npy_preprocess((size,)),
        postprocess=lambda out: {"echo": np.asarray(out).tolist()},
        batch_buckets=tuple(buckets))


def build_unet(name: str = "landcover", tile: int = 256,
               widths=(32, 64, 128), num_classes: int = 8, buckets=IMAGE_BUCKETS,
               fused_postprocess: bool = True,
               return_classmap: bool = False,
               wire: str = "rgb8", **_) -> ServableModel:
    """Land-cover segmentation (BASELINE.json config #2).

    ``return_classmap`` adds the classified tile itself to the response as a
    base64 PNG (the reference's land-cover APIs return classified tiles, not
    just statistics). Off by default: the histogram API then fetches only
    B·C int32 counts from the device — on a remote-attached TPU the uint8
    map would otherwise dominate the device→host link (H·W bytes/example vs
    ~32).

    ``wire`` selects the host→device batch encoding: ``rgb8`` (raw uint8
    pixels, 3 B/px) or ``yuv420`` (planar JPEG-convention YCbCr with 2×2
    chroma, 1.5 B/px — halves the h2d bytes that bound throughput on a
    remote-attached device; reconstruction fuses into the first conv on
    device, ``ops/yuv.py``). Clients ship the same payloads either way:
    single requests as image/npy, batch stacks as (N, H, W, 3) — stack
    items convert to planes at ingestion (``stack_adapter``).
    """
    from ..models import create_unet
    from ..ops.pallas import fused_seg_postprocess, normalize_image

    _check_wire(wire, fused_postprocess, "fused_postprocess")

    model, params = create_unet(tile=tile, widths=tuple(widths),
                                num_classes=num_classes)

    def fused_postprocess_fn(out):
        # One response contract for every fused ingestion wire.
        counts = np.asarray(out["counts"])
        result = {"class_histogram":
                  {int(c): int(n) for c, n in enumerate(counts) if n}}
        if return_classmap:
            result["classmap_png"] = encode_classmap_png(
                np.asarray(out["classmap"]))
        return result

    if wire in ("yuv420", "dct"):
        def on_normalized(p, x):
            return fused_seg_postprocess(model.apply(p, x),
                                         with_classmap=return_classmap)

        build = _yuv_servable if wire == "yuv420" else _dct_servable
        return build(name, params, on_normalized, tile, tile,
                     fused_postprocess_fn, buckets)

    if fused_postprocess:
        def apply_fn(p, batch):
            x = normalize_image(batch)
            return fused_seg_postprocess(model.apply(p, x),
                                         with_classmap=return_classmap)

        postprocess = fused_postprocess_fn
        input_dtype = np.uint8
        preprocess = _image_preprocess((tile, tile, 3), np.uint8)
    else:
        from ..models import segment_logits_to_classes

        def apply_fn(p, batch):
            return model.apply(p, batch)

        def postprocess(logits):
            classes = np.asarray(segment_logits_to_classes(logits[None])[0])
            values, counts = np.unique(classes, return_counts=True)
            result = {"class_histogram":
                      {int(v): int(c) for v, c in zip(values, counts)}}
            if return_classmap:  # same response contract as the fused path
                result["classmap_png"] = encode_classmap_png(classes)
            return result

        input_dtype = np.float32
        preprocess = _image_preprocess((tile, tile, 3))

    return ServableModel(
        name=name, apply_fn=apply_fn, params=params,
        input_shape=(tile, tile, 3), input_dtype=input_dtype,
        preprocess=preprocess, postprocess=postprocess,
        batch_buckets=tuple(buckets))


def build_resnet(name: str = "classifier", image_size: int = 224,
                 num_classes: int = 1000, stage_sizes=(3, 4, 6, 3),
                 width: int = 64, labels: list | None = None,
                 buckets=IMAGE_BUCKETS, fused_normalize: bool = True,
                 wire: str = "rgb8", **_) -> ServableModel:
    """Batched species classification (BASELINE.json config #4).

    ``fused_normalize`` (default): clients ship uint8 pixels — 4x less
    transfer + host copy than float32 — and the cast/scale to [0,1] runs
    on-device in one VMEM pass (``ops/pallas/normalize_image``), the same
    ingestion design as the landcover bench path. Weights are unaffected
    (normalization reproduces the float input the model trained on).

    ``wire="yuv420"`` goes further: planar 4:2:0 chroma on the wire (half
    the h2d bytes again; ``ops/yuv.py``). Opt-in; batch stacks and the
    crops handoff keep shipping (N, H, W, 3) — items convert at ingestion.
    """
    from ..models.resnet import ResNet

    model = ResNet(stage_sizes=tuple(stage_sizes), num_classes=num_classes,
                   width=width)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, image_size, image_size, 3),
                                    np.float32))

    def postprocess(logits):
        logits = np.asarray(logits, np.float64)
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        top = int(np.argmax(probs))
        return {"class_id": top,
                "label": labels[top] if labels else str(top),
                "confidence": float(probs[top])}

    _check_wire(wire, fused_normalize, "fused_normalize")
    if wire in ("yuv420", "dct"):
        build = _yuv_servable if wire == "yuv420" else _dct_servable
        return build(name, variables, model.apply,
                     image_size, image_size, postprocess, buckets)

    apply_fn, input_dtype = _maybe_fused_uint8(model.apply, fused_normalize)
    return ServableModel(
        name=name, apply_fn=apply_fn, params=variables,
        input_shape=(image_size, image_size, 3), input_dtype=input_dtype,
        preprocess=_image_preprocess((image_size, image_size, 3),
                                     input_dtype),
        postprocess=postprocess, batch_buckets=tuple(buckets))


def _maybe_fused_uint8(apply_fn, fused: bool):
    """uint8-ingestion wrapper: on-device normalize to [0,1] before the
    model (ops/pallas/normalize_image); returns (apply_fn, input_dtype)."""
    if not fused:
        return apply_fn, np.float32
    from ..ops.pallas import normalize_image

    def fused_apply(p, batch):
        return apply_fn(p, normalize_image(batch))

    return fused_apply, np.uint8


def _check_wire(wire: str, fused: bool, fused_flag: str) -> None:
    """Uniform wire validation for the image families: unknown wire values
    and the compressed-wire-without-fused-ingestion conflict both fail at
    build time (wire reconstruction IS the fused ingestion — disabling it
    while asking for a compressed wire is contradictory, not overridable)."""
    if wire not in ("rgb8", "yuv420", "dct"):
        raise ValueError(f"wire must be rgb8|yuv420|dct, got {wire!r}")
    if wire in ("yuv420", "dct") and not fused:
        raise ValueError(f"wire={wire!r} requires {fused_flag}=True")


def _yuv_servable(name: str, params, apply_on_normalized, h: int, w: int,
                  postprocess, buckets) -> ServableModel:
    """YUV 4:2:0 wire servable for an (H, W, 3) model whose
    ``apply_on_normalized`` consumes [0,1] float RGB: clients ship the usual
    image/npy payloads, the host converts to planar 4:2:0 (half the h2d
    bytes of raw uint8 RGB), the device reconstructs fused into the model's
    first op (``ops/yuv.py``). One construction point for every family."""
    from ..ops.yuv import (rgb_to_yuv420, yuv420_nbytes, yuv420_to_rgb,
                           yuv420_to_rgb_numpy)

    if h % 2 or w % 2:
        # Fail at BUILD time: an odd size would construct fine and then die
        # in preprocess on every request.
        raise ValueError(f"wire='yuv420' needs even dims, got {h}x{w}")
    rgb_pre = _image_preprocess((h, w, 3), np.uint8)

    def preprocess(body: bytes, content_type: str):
        return rgb_to_yuv420(rgb_pre(body, content_type))

    def apply_fn(p, batch):
        return apply_on_normalized(p, yuv420_to_rgb(batch, h, w))

    return ServableModel(
        name=name, apply_fn=apply_fn, params=params,
        input_shape=(yuv420_nbytes(h, w),), input_dtype=np.uint8,
        preprocess=preprocess, postprocess=postprocess,
        batch_buckets=tuple(buckets),
        # Batch stacks keep shipping (N, H, W, 3); each item converts to
        # planes at ingestion (serve_batch).
        stack_item_shape=(h, w, 3), stack_item_dtype=np.uint8,
        stack_adapter=rgb_to_yuv420,
        # Host consumers of the preprocessed example (a crops handoff
        # cropping this stage's input) get the RGB image back.
        example_decoder=lambda flat: yuv420_to_rgb_numpy(flat, h, w))


def _dct_servable(name: str, params, apply_on_normalized, h: int, w: int,
                  postprocess, buckets) -> ServableModel:
    """DCT-truncation wire servable (``ops/dct.py``): clients ship the usual
    image/npy payloads, the host packs quantized K×K DCT coefficients
    (0.375 B/px — 4× less h2d than yuv420, 8× less than raw RGB), the
    device decodes with dequant + per-block IDCT matmuls fused into the
    model's first op. Same construction contract as ``_yuv_servable``."""
    from ..ops.dct import (dct_nbytes, dct_to_rgb, dct_to_rgb_numpy,
                           rgb_to_dct)

    if h % 16 or w % 16:
        # Fail at BUILD time (8-px luma blocks × 2× chroma subsampling).
        raise ValueError(f"wire='dct' needs dims divisible by 16, "
                         f"got {h}x{w}")
    rgb_pre = _image_preprocess((h, w, 3), np.uint8)

    def preprocess(body: bytes, content_type: str):
        return rgb_to_dct(rgb_pre(body, content_type))

    def apply_fn(p, batch):
        return apply_on_normalized(p, dct_to_rgb(batch, h, w))

    return ServableModel(
        name=name, apply_fn=apply_fn, params=params,
        input_shape=(dct_nbytes(h, w),), input_dtype=np.int8,
        preprocess=preprocess, postprocess=postprocess,
        batch_buckets=tuple(buckets),
        stack_item_shape=(h, w, 3), stack_item_dtype=np.uint8,
        stack_adapter=rgb_to_dct,
        example_decoder=lambda flat: dct_to_rgb_numpy(flat, h, w))


def build_detector(name: str = "megadetector", image_size: int = 512,
                   widths=(64, 128, 256), max_detections: int = 64,
                   score_threshold: float = 0.2, buckets=DETECTOR_BUCKETS,
                   fused_normalize: bool = True,
                   wire: str = "rgb8", **_) -> ServableModel:
    """Camera-trap detection (BASELINE.json config #3, MegaDetector slot).

    ``fused_normalize``: uint8 ingestion + on-device [0,1] scaling (see
    ``build_resnet``) — a camera-trap JPEG pipeline ships bytes, not floats.
    ``wire="yuv420"``: planar 4:2:0 on the wire, halving h2d bytes again —
    the detector ships the fattest tiles of any family (H·W·3 at 512²), so
    this is where a bandwidth-bound link gains the most. Opt-in; batch
    stacks keep shipping (N, H, W, 3) — items convert at ingestion.
    """
    from ..models import CenterNetDetector, decode_detections

    model = CenterNetDetector(widths=tuple(widths))
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, image_size, image_size, 3), np.float32))

    def raw_apply(p, batch):
        return decode_detections(model.apply(p, batch),
                                 max_detections=max_detections)

    def postprocess(out):
        scores = np.asarray(out["scores"])
        keep = scores >= score_threshold
        return {"detections": [
            {"box": np.asarray(out["boxes"])[i].tolist(),
             "score": float(scores[i]),
             "class_id": int(np.asarray(out["classes"])[i])}
            for i in np.nonzero(keep)[0]]}

    _check_wire(wire, fused_normalize, "fused_normalize")
    if wire in ("yuv420", "dct"):
        build = _yuv_servable if wire == "yuv420" else _dct_servable
        return build(name, params, raw_apply,
                     image_size, image_size, postprocess, buckets)

    apply_fn, input_dtype = _maybe_fused_uint8(raw_apply, fused_normalize)
    return ServableModel(
        name=name, apply_fn=apply_fn, params=params,
        input_shape=(image_size, image_size, 3), input_dtype=input_dtype,
        preprocess=_image_preprocess((image_size, image_size, 3),
                                     input_dtype),
        postprocess=postprocess, batch_buckets=tuple(buckets))


def build_vit(name: str = "vit", image_size: int = 224, patch: int = 16,
              dim: int = 384, depth: int = 12, heads: int = 6,
              num_classes: int = 1000, buckets=IMAGE_BUCKETS, **_
              ) -> ServableModel:
    from ..models import create_vit

    model, params = create_vit(image_size=image_size, patch=patch, dim=dim,
                               depth=depth, heads=heads,
                               num_classes=num_classes)

    def postprocess(logits):
        top = int(np.argmax(np.asarray(logits)))
        return {"class_id": top}

    return ServableModel(
        name=name, apply_fn=model.apply, params=params,
        input_shape=(image_size, image_size, 3),
        preprocess=_image_preprocess((image_size, image_size, 3)),
        postprocess=postprocess, batch_buckets=tuple(buckets))


def _check_token_ids(arr: np.ndarray, vocab_size: int) -> None:
    """THE token-id validation, shared by the single-item and batch-stack
    wires so they cannot drift: integer dtype (floats would silently
    truncate fractional ids) and range (the on-device Embed gather CLAMPS
    out-of-bounds indices — XLA semantics — so an unchecked bad id scores
    silently wrong instead of failing). Must run on the RAW payload,
    before any cast: an int64 id ≥ 2³² wraps into range under int32."""
    if not np.issubdtype(arr.dtype, np.integer):
        raise ValueError(f"token payload must be integer, got {arr.dtype}")
    if arr.size and (int(arr.min()) < 0 or int(arr.max()) >= vocab_size):
        raise ValueError(
            f"token ids must be in [0, {vocab_size}); got "
            f"[{int(arr.min())}, {int(arr.max())}]")


def _token_preprocess(seq_len: int, vocab_size: int):
    """Payload decoder for token-id sequences: any integer npy of shape
    (S,) in ``[0, vocab_size)``. Clients ship the narrowest integer dtype
    they like (uint16 for vocabs ≤64k — 2 bytes/token on the HTTP wire);
    the device batch is int32 either way. Out-of-range ids fail that one
    task at preprocess, never the batch."""

    def preprocess(body: bytes, content_type: str):
        arr = np.load(io.BytesIO(body))
        if arr.shape != (seq_len,):
            raise ValueError(f"expected ({seq_len},), got {arr.shape}")
        _check_token_ids(arr, vocab_size)
        return arr.astype(np.int32)
    return preprocess


def _sequence_input_contract(seq_len: int, input_dim: int,
                             vocab_size: int | None,
                             feature_dtype=np.float32):
    """``(input_shape, input_dtype, preprocess, stack_kwargs)`` for the
    sequence families' shared wire contract: token ids when ``vocab_size``
    is set, float feature sequences otherwise. One helper so seqformer and
    moe cannot drift.

    Token mode's ``stack_kwargs`` install ``_check_token_ids`` as the
    batch-stack validator — it runs on the RAW stack, before the decode
    path's cast to the device dtype (a post-cast check would pass
    wrapped-into-range ids). Value-level stack validation failing the
    whole stack matches the image families' NaN behavior."""
    if vocab_size is not None:
        return ((seq_len,), np.dtype(np.int32),
                _token_preprocess(seq_len, vocab_size),
                {"stack_validator":
                 lambda arr: _check_token_ids(arr, vocab_size)})
    fdt = np.dtype(feature_dtype)
    return ((seq_len, input_dim), fdt,
            _npy_preprocess((seq_len, input_dim), fdt), {})


def build_seqformer(name: str = "longcontext", seq_len: int = 4096,
                    input_dim: int = 64, dim: int = 128, depth: int = 2,
                    heads: int = 8, num_classes: int = 16,
                    attention: str = "auto", causal: bool = False,
                    buckets=(1, 8), mesh=None,
                    wire_dtype: str = "float16",
                    vocab_size: int | None = None, **_) -> ServableModel:
    """Long-context sequence classification (SURVEY.md §5 long-context slot):
    attention over the payload runs ring/Ulysses sequence-parallel over the
    mesh's sp axis when it has one.

    Two input contracts:

    - ``vocab_size=N`` — **token mode, the production wire**: payload is an
      (S,) integer npy of ids, embedded on-device (``nn.Embed``). 2
      bytes/token on the wire vs 128 bytes/token of pre-embedded f16
      features at D=64 — on a remote-attached chip this turns the family
      from link-bound to compute-bound (r3: the feature wire saturated the
      tunnel at 524 kB/request, 1.15× anchor).
    - ``vocab_size=None`` — feature mode: (S, input_dim) float sequences,
      e.g. embedded acoustic/satellite time series produced upstream.
      ``wire_dtype`` (float16 default, float32 accepted) carries the batch:
      the model computes bf16 regardless and f16's 10 mantissa bits exceed
      bf16's 7, so the half wire halves bytes without touching the math.
      Payloads outside f16 range fail that task at preprocess."""
    from ..models.seqformer import create_seqformer

    wdt = np.dtype(wire_dtype)
    if wdt not in (np.dtype(np.float16), np.dtype(np.float32)):
        raise ValueError(f"wire_dtype must be float16/float32, got {wire_dtype}")

    model, params = create_seqformer(
        seq_len=seq_len, input_dim=input_dim, dim=dim, depth=depth,
        heads=heads, num_classes=num_classes, mesh=mesh, attention=attention,
        causal=causal, vocab_size=vocab_size)

    def postprocess(logits):
        logits = np.asarray(logits, np.float64)
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        top = int(np.argmax(probs))
        return {"class_id": top, "confidence": float(probs[top])}

    input_shape, input_dtype, preprocess, stack_kwargs = (
        _sequence_input_contract(seq_len, input_dim, vocab_size,
                                 feature_dtype=wdt))
    return ServableModel(
        name=name, apply_fn=model.apply, params=params,
        input_shape=input_shape, input_dtype=input_dtype,
        preprocess=preprocess,
        postprocess=postprocess, batch_buckets=tuple(buckets),
        **stack_kwargs)


def build_moe(name: str = "moe", seq_len: int = 1024, input_dim: int = 64,
              dim: int = 128, depth: int = 2, heads: int = 8,
              num_experts: int = 8, num_classes: int = 16,
              attention: str = "flash", dispatch: str = "dense",
              capacity_factor: float = 1.25, buckets=(1, 8), mesh=None,
              vocab_size: int | None = None, **_) -> ServableModel:
    """Mixture-of-Experts sequence classification — the expert-parallel
    family: expert tensors shard over the mesh's ``ep`` axis
    (``models/moe.py``), composing with dp/fsdp exactly like seqformer's sp.
    ``dispatch="capacity"`` serves the GShard-style static-capacity path.
    ``vocab_size`` switches to the token-id wire (same contract as the
    seqformer family: (S,) integer npy, embedded on-device)."""
    from ..models.moe import MOE_EP_RULES, create_moe

    model, params = create_moe(
        seq_len=seq_len, input_dim=input_dim, dim=dim, depth=depth,
        heads=heads, num_experts=num_experts, num_classes=num_classes,
        mesh=mesh, attention=attention, dispatch=dispatch,
        capacity_factor=capacity_factor, vocab_size=vocab_size)

    input_shape, input_dtype, preprocess, stack_kwargs = (
        _sequence_input_contract(seq_len, input_dim, vocab_size))
    return ServableModel(
        name=name, apply_fn=model.apply, params=params,
        input_shape=input_shape, input_dtype=input_dtype,
        preprocess=preprocess,
        postprocess=_classification_postprocess(),
        batch_buckets=tuple(buckets),
        # ModelRuntime.register re-places every param on its mesh; the rules
        # ride along so expert sharding survives registration.
        param_sharding_rules=MOE_EP_RULES, **stack_kwargs)


FAMILIES = {
    "echo": build_echo,
    "unet": build_unet,
    "resnet": build_resnet,
    "detector": build_detector,
    "vit": build_vit,
    "seqformer": build_seqformer,
    "moe": build_moe,
}


def build_servable(family: str, **kwargs) -> ServableModel:
    if family not in FAMILIES:
        raise ValueError(
            f"unknown model family {family!r}; valid: {sorted(FAMILIES)}")
    return FAMILIES[family](**kwargs)
