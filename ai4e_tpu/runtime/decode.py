"""Continuous-batching decode engine — iteration-level scheduling.

The MicroBatcher's contract is whole-batch-in/whole-batch-out: a batch
is cut, runs to completion, fans out. Autoregressive decoding under that
contract is a throughput disaster — one 512-token sequence holds a batch
of 8-token completions hostage for its entire decode. This engine is the
second serving path, beside the batcher, where scheduling happens
*inside* the device loop:

- new requests join the running batch BETWEEN decode steps: a prefill is
  admitted into a free KV-cache slot the moment one exists (padded to
  the prompt-bucket ladder, ``ladder.DECODE_PROMPT_BUCKETS`` discipline);
- every decode step advances EVERY active sequence by one token; each
  token is handed to the request's ``on_token`` callback the moment it
  exists (the worker publishes it as a ``chunk`` event through the
  ``TaskEventHub``, so ``GET /task/{id}/events`` streams tokens live);
- finished sequences (EOS / ``max_new_tokens`` / KV-cache slot full)
  leave between steps and free their slot immediately;
- a per-step deadline sweep frees an EXPIRED sequence's slot mid-decode
  instead of completing it late (admission/: dead work never holds a
  slot), and a cancelled waiter (client gone) is retired the same way;
- a hot weight reload (``params_version`` bump) invalidates the pooled
  KV cache — same contract as rescache — and active sequences are
  re-prefilled from their token history under the new weights, keeping
  their slots.

Slot conservation is THE invariant (tests/test_race_regressions.py):
a slot is never double-assigned, never leaked, and freed exactly once.
Every release funnels through ``_retire`` — a single-segment method
(docs/concurrency.md): the ``done`` guard and the slot release share one
atomicity segment, and every post-``await`` consumer re-checks ``done``
before acting on a sequence (the step/prefill awaits are the suspension
windows a cancel or expiry sweep can slot into).

Backpressure: ``pending_count`` at ``max_pending`` → ``submit`` raises
``DecodeSaturated`` and the worker answers 503 through the existing
admission path, exactly like ``BatcherSaturated``.

This module imports neither JAX nor numpy: the device work lives behind
the backend interface (``runtime/kvcache.py``), so the race-smoke CI job
(no JAX toolchain) explores the real engine under the deterministic
scheduler.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial

from ..admission.deadline import DeadlineExceeded, priority_name
from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..rollout.drain import DrainingError

log = logging.getLogger("ai4e_tpu.decode")


class DecodeSaturated(RuntimeError):
    """No pending capacity — the worker's admission path answers 503."""


class SlotError(RuntimeError):
    """A slot-conservation violation (double release / foreign release /
    double assignment) — raised immediately so the interleaving explorer
    and the chaos invariants see the exact violating step."""


class SlotPool:
    """KV-cache slot accounting. Pure bookkeeping — the device-side
    buffers live in ``runtime/kvcache.py``; this object is the single
    source of truth for which slots are free, and it RAISES on any
    conservation violation instead of silently absorbing it."""

    def __init__(self, slots: int):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = slots
        self._free = list(range(slots - 1, -1, -1))  # LIFO: slot 0 first
        self._busy: set[int] = set()

    def acquire(self) -> int | None:
        """A free slot, or None when the pool is full."""
        if not self._free:
            return None
        slot = self._free.pop()
        if slot in self._busy:
            raise SlotError(f"slot {slot} double-assigned")
        self._busy.add(slot)
        return slot

    def release(self, slot: int) -> None:
        if slot not in self._busy:
            raise SlotError(
                f"slot {slot} released while not held (double free or "
                f"foreign free); busy={sorted(self._busy)}")
        self._busy.remove(slot)
        self._free.append(slot)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def busy_count(self) -> int:
        return len(self._busy)

    def check_conservation(self) -> None:
        """Every slot is exactly one of free/busy — the post-run check
        the race regressions assert."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise SlotError(f"free list holds duplicates: {self._free}")
        if free & self._busy:
            raise SlotError(
                f"slots both free and busy: {sorted(free & self._busy)}")
        if len(free) + len(self._busy) != self.slots:
            raise SlotError(
                f"slot leak: {len(free)} free + {len(self._busy)} busy "
                f"!= {self.slots}")


@dataclass
class _Sequence:
    """One streaming request's decode state."""

    prompt: tuple  # int token ids
    future: asyncio.Future
    max_new_tokens: int
    on_token: object = None       # callable (index, token) -> None
    priority: int = 0
    deadline_at: float = 0.0      # absolute unix seconds; 0.0 = none
    ledger: object = None         # observability.ledger.HopLedger | None
    tokens: list = field(default_factory=list)  # generated ids
    slot: int | None = None
    position: int = 0             # next KV write index (= prompt + generated)
    done: bool = False
    enqueued: float = field(default_factory=time.perf_counter)
    last_token_at: float = 0.0


class DecodeEngine:
    """The iteration-level scheduling loop over a decode-step backend.

    ``backend`` (``runtime/kvcache.py`` for the real device; tests
    inject fakes) exposes:

    - ``slots`` / ``max_len`` / ``eos_id`` / ``name``;
    - ``params_version`` (property): bumped by hot reload — the pooled
      cache key, checked every tick;
    - ``reset_cache()``: drop + reallocate the pooled cache (reload
      invalidation);
    - ``prefill_into(slot, tokens) -> first generated token id``;
    - ``step(tokens, positions, active) -> next token id per slot``
      (plain int lists — the backend owns array conversion).

    Backend methods may be sync (run on the engine's single device
    executor thread — the device is the serial resource, same discipline
    as the batcher) or async (the race tests' fakes, explored under the
    virtual loop).

    ``continuous=False`` is the whole-batch baseline the bench A/Bs
    against: admission only when the pool is EMPTY, so a running batch
    drains completely before anyone joins — the old contract, kept
    measurable.
    """

    def __init__(self, backend, max_pending: int = 64,
                 continuous: bool = True,
                 metrics: MetricsRegistry | None = None):
        self.backend = backend
        self.max_pending = max_pending
        self.continuous = continuous
        self.pool = SlotPool(backend.slots)
        self._queue: deque[_Sequence] = deque()
        self._active: dict[int, _Sequence] = {}
        self._wakeup = asyncio.Event()
        self._stop = False
        # Rollout drain (rollout/drain.py): stop admitting prefills but
        # let ACTIVE sequences decode to completion — bounded by the
        # caller's drain budget, after which ``force_drain`` retires the
        # stragglers (each redelivers through the broker per task).
        self._draining = False
        self._loop_task: asyncio.Task | None = None
        self._executor = None
        self._cache_version = None
        self.metrics = metrics or DEFAULT_REGISTRY
        name = getattr(backend, "name", "lm")
        self._model = name
        self._ttft = self.metrics.histogram(
            "ai4e_decode_ttft_seconds",
            "Submit-to-first-token latency per streaming request")
        self._intertoken = self.metrics.histogram(
            "ai4e_decode_intertoken_seconds",
            "Gap between consecutive tokens of one sequence")
        self._step_hist = self.metrics.histogram(
            "ai4e_decode_step_seconds",
            "Device time per engine step, by phase (prefill/decode)")
        self._occupancy = self.metrics.gauge(
            "ai4e_decode_slot_occupancy",
            "Occupied KV-cache slots / total slots per model")
        self._pending_gauge = self.metrics.gauge(
            "ai4e_decode_pending",
            "Streaming requests waiting for a KV-cache slot")
        self._tokens_total = self.metrics.counter(
            "ai4e_decode_tokens_total", "Generated tokens per model")
        self._sequences_total = self.metrics.counter(
            "ai4e_decode_sequences_total",
            "Finished sequences by model and outcome")
        self._reprefills_total = self.metrics.counter(
            "ai4e_decode_reprefills_total",
            "Active sequences re-prefilled after a hot-reload "
            "KV-cache invalidation")
        self._expired_total = self.metrics.counter(
            "ai4e_admission_expired_total",
            "Requests dropped on deadline expiry, by hop/priority")

    # -- request side ------------------------------------------------------

    @property
    def pending_count(self) -> int:
        return len(self._queue)

    @property
    def active_count(self) -> int:
        return len(self._active)

    async def submit(self, prompt, max_new_tokens: int, on_token=None,
                     priority: int = 0, deadline_at: float = 0.0,
                     ledger=None) -> list:
        """Queue one streaming generation; resolves to the generated
        token ids. ``on_token(index, token_id)`` fires on the engine
        loop the moment each token exists — the worker publishes chunks
        from it. Cancelling the await retires the sequence and frees its
        slot at the next sweep."""
        if self._stop:
            raise RuntimeError("decode engine stopped")
        if self._draining:
            raise DrainingError("decode engine draining; submit refused")
        if self.pending_count >= self.max_pending:
            raise DecodeSaturated(
                f"decode queue at {self.pending_count}/{self.max_pending} "
                f"pending")
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) >= self.backend.max_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens leaves no room to "
                f"generate under the KV-cache length {self.backend.max_len}")
        fut = asyncio.get_running_loop().create_future()
        seq = _Sequence(prompt=prompt, future=fut,
                        max_new_tokens=max_new_tokens, on_token=on_token,
                        priority=priority, deadline_at=deadline_at,
                        ledger=ledger)
        self._queue.append(seq)
        self._pending_gauge.set(self.pending_count, model=self._model)
        self._wakeup.set()
        return await fut

    def cancel(self, future: asyncio.Future) -> None:
        """Retire the sequence awaiting ``future`` (client gone). The
        sweep also catches cancelled futures; this frees the slot
        without waiting for the next tick."""
        for seq in list(self._active.values()) + list(self._queue):
            if seq.future is future:
                self._retire(seq, "cancelled")
                return

    # -- drain (rollout/drain.py drives these; docs/deployment.md) ---------

    def begin_drain(self) -> int:
        """Stop admitting prefills and retire every QUEUED sequence with
        ``DrainingError`` (each redelivers through the broker per task);
        active sequences keep decoding — ``drain_complete`` turns true
        when the last one finishes. Flip + retire are one synchronous
        step, so a concurrently scheduled ``_admit`` cannot prefill a
        sequence this sweep already failed."""
        self._draining = True
        retired = 0
        for seq in list(self._queue):
            if not seq.done:
                self._retire(seq, "cancelled",
                             error=DrainingError(
                                 "decode engine draining; redeliver"))
                retired += 1
        self._wakeup.set()
        return retired

    @property
    def drain_complete(self) -> bool:
        """Draining AND quiesced: no queued, no active sequences."""
        return self._draining and not self._active and not self._queue

    def force_drain(self) -> int:
        """Retire the ACTIVE stragglers past the drain budget with
        ``DrainingError`` — each redelivers through the broker per task,
        the PR 17 poisoned-row path."""
        forced = 0
        for seq in list(self._active.values()):
            if not seq.done:
                self._retire(seq, "cancelled",
                             error=DrainingError(
                                 "decode drain budget exhausted; "
                                 "redeliver"))
                forced += 1
        return forced

    def resume_from_drain(self) -> None:
        """Re-arm after an aborted drain (rollback re-weights the worker
        back into service without a process restart)."""
        self._draining = False
        self._wakeup.set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._stop = False
        self._loop_task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stop = True
        self._wakeup.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        for seq in list(self._active.values()) + list(self._queue):
            self._retire(seq, "cancelled",
                         error=RuntimeError("decode engine stopped"))
        if self._executor is not None:
            self._executor.shutdown(wait=True)

    # -- engine loop -------------------------------------------------------

    async def _run(self) -> None:
        while not self._stop:
            if not self._active and not self._queue:
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    continue
            if self._stop:
                return
            try:
                await self._tick()
            except Exception:  # noqa: BLE001 — a backend crash fails the affected sequences below, never the loop
                log.exception("decode tick failed; failing active sequences")
                for seq in list(self._active.values()):
                    self._retire(seq, "failed",
                                 error=RuntimeError("decode step failed"))

    async def _tick(self) -> None:
        """One scheduling iteration: reload check → expiry/cancel sweep →
        admission (prefill into free slots) → one decode step."""
        await self._check_reload()
        self._sweep()
        await self._admit()
        await self._step()

    async def _check_reload(self) -> None:
        """Hot-reload invalidation: a ``params_version`` bump makes the
        pooled cache stale (it was computed under the old weights — the
        rescache contract). Re-prefill every active sequence from its
        token history under the new weights; slots are kept, never
        re-acquired, so conservation holds across the invalidation."""
        version = self.backend.params_version
        if version == self._cache_version:
            return
        first_attach = self._cache_version is None
        self._cache_version = version
        if first_attach and not self._active:
            return  # engine's first tick ever: nothing to invalidate
        reset = self.backend.reset_cache()
        if inspect.isawaitable(reset):
            await reset
        for seq in list(self._active.values()):
            if seq.done:
                continue
            history = seq.prompt + tuple(seq.tokens)
            if len(history) >= self.backend.max_len:
                # No room to re-derive the next token's KV: the sequence
                # was about to hit the context bound anyway.
                self._retire(seq, "completed")
                continue
            t0 = time.perf_counter()
            try:
                token = await self._call(self.backend.prefill_into,
                                         seq.slot, list(history))
            except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — delivered to the sequence's waiter as its failure
                self._retire(seq, "failed", error=exc)
                continue
            self._step_hist.observe(time.perf_counter() - t0,
                                    phase="prefill", model=self._model)
            if seq.done:
                continue  # retired (cancel/expiry) while re-prefilling
            seq.position = len(history)
            self._reprefills_total.inc(model=self._model)
            self._note_token(seq, int(token))

    def _sweep(self) -> None:
        """Expiry + cancellation sweep, every iteration — single
        segment, no suspension points: the decision and the slot release
        cannot interleave with anything (docs/concurrency.md)."""
        now = time.time()
        for seq in list(self._active.values()) + list(self._queue):
            if seq.done:
                continue
            if seq.future.done():
                # Waiter cancelled (client disconnected): nothing to
                # deliver tokens to — free the slot now.
                self._retire(seq, "cancelled")
            elif seq.deadline_at and seq.deadline_at <= now:
                self._expired_total.inc(hop="decode",
                                        priority=priority_name(seq.priority))
                self._retire(seq, "expired",
                             error=DeadlineExceeded("decode",
                                                    seq.deadline_at))

    async def _admit(self) -> None:
        """Prefill queued requests into free KV-cache slots — BETWEEN
        decode steps, the continuous-batching join. Whole-batch mode
        (``continuous=False``) gates admission on an EMPTY pool (checked
        once at entry), then fills every slot it can: the old whole-
        batch-in/whole-batch-out contract, kept measurable as the bench
        baseline."""
        if self._draining:
            # Anything that raced past the submit-side refusal is retired
            # here rather than prefilled onto a leaving worker.
            for seq in list(self._queue):
                if not seq.done:
                    self._retire(seq, "cancelled",
                                 error=DrainingError(
                                     "decode engine draining; redeliver"))
            return
        if not self.continuous and self._active:
            return
        while self._queue:
            slot = self.pool.acquire()
            if slot is None:
                return
            seq = self._queue.popleft()
            self._pending_gauge.set(self.pending_count, model=self._model)
            if seq.done or seq.future.done():
                # Swept/cancelled while queued: the slot was never its.
                self.pool.release(slot)
                if not seq.done:
                    self._retire(seq, "cancelled")
                continue
            seq.slot = slot
            self._active[slot] = seq
            self._occupancy.set(self.pool.busy_count / self.pool.slots,
                                model=self._model)
            t0 = time.perf_counter()
            try:
                token = await self._call(self.backend.prefill_into,
                                         slot, list(seq.prompt))
            except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — delivered to the sequence's waiter as its failure
                self._retire(seq, "failed", error=exc)
                continue
            self._step_hist.observe(time.perf_counter() - t0,
                                    phase="prefill", model=self._model)
            if seq.done:
                continue  # re-check after the await: retired mid-prefill
            seq.position = len(seq.prompt)
            self._note_token(seq, int(token))

    async def _step(self) -> None:
        """One decode step over the whole slot pool: every active
        sequence advances one token; inactive slots ride along masked."""
        if not self._active:
            return
        snapshot = [(slot, seq, seq.position)
                    for slot, seq in sorted(self._active.items())
                    if not seq.done]
        if not snapshot:
            return
        tokens = [0] * self.pool.slots
        positions = [0] * self.pool.slots
        active = [False] * self.pool.slots
        for slot, seq, position in snapshot:
            tokens[slot] = seq.tokens[-1]
            positions[slot] = position
            active[slot] = True
        t0 = time.perf_counter()
        out = await self._call(self.backend.step, tokens, positions, active)
        self._step_hist.observe(time.perf_counter() - t0, phase="decode",
                                model=self._model)
        for slot, seq, position in snapshot:
            if seq.done or seq.slot != slot:
                continue  # re-check after the await: retired mid-step
            seq.position = position + 1
            self._note_token(seq, int(out[slot]))

    # -- bookkeeping (single-segment: no suspension points below) ---------

    def _note_token(self, seq: _Sequence, token: int) -> None:
        """Account one generated token: callback (chunk emission), TTFT /
        inter-token latency, and the finish decision (EOS, token budget,
        KV-cache slot full)."""
        now = time.perf_counter()
        first = not seq.tokens
        seq.tokens.append(token)
        self._tokens_total.inc(model=self._model)
        if first:
            ttft = now - seq.enqueued
            self._ttft.observe(ttft, model=self._model)
            if seq.ledger is not None:
                # ONE chunk stamp per request (the ledger caps at 128
                # events — a 512-token stream must not eat the budget):
                # the first token, with TTFT as the duration.
                seq.ledger.stamp("chunk", "decode", ms=ttft * 1e3,
                                 reason="first token")
        else:
            self._intertoken.observe(now - seq.last_token_at,
                                     model=self._model)
        seq.last_token_at = now
        if seq.on_token is not None:
            try:
                seq.on_token(len(seq.tokens) - 1, token)
            except Exception:  # noqa: BLE001 — chunk fan-out is fail-open telemetry, never a decode error
                log.debug("on_token callback failed", exc_info=True)
        eos = getattr(self.backend, "eos_id", None)
        if (len(seq.tokens) >= seq.max_new_tokens
                or (eos is not None and token == eos)
                or seq.position >= self.backend.max_len):
            self._retire(seq, "completed")

    def _retire(self, seq: _Sequence, outcome: str, error=None) -> None:
        """THE slot-release funnel — single segment (no awaits), so the
        ``done`` guard and the release are atomic; idempotent, so every
        path (finish, expiry, cancel, failure, shutdown) may call it and
        the slot is still freed exactly once."""
        if seq.done:
            return
        seq.done = True
        if seq.slot is not None:
            self._active.pop(seq.slot, None)
            self.pool.release(seq.slot)
            seq.slot = None
            self._occupancy.set(self.pool.busy_count / self.pool.slots,
                                model=self._model)
        else:
            try:
                self._queue.remove(seq)
            except ValueError:
                pass  # already popped by admission
            self._pending_gauge.set(self.pending_count, model=self._model)
        self._sequences_total.inc(model=self._model, outcome=outcome)
        if not seq.future.done():
            if error is not None:
                seq.future.set_exception(error)
            else:
                seq.future.set_result(list(seq.tokens))

    async def _call(self, fn, /, *args):
        """Invoke a backend method: async backends (race-test fakes)
        await inline; sync backends (the JAX runtime) run on the single
        device executor thread — the device is the serial resource."""
        if inspect.iscoroutinefunction(fn):
            return await fn(*args)
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-decode")
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, partial(fn, *args))
