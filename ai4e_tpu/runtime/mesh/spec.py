"""Declarative serving-mesh spec — the shape a worker's endpoint serves.

``MeshLayout`` is the operator-facing grammar (``AI4E_RUNTIME_MESH_SPEC``,
docs/mesh_serving.md): a dp×tp×sp shape string like ``"dp=8"`` or
``"dp=2,tp=2"``, validated before any device work happens and exposed on
``GET /v1/models`` so clients and the orchestrator can reason about the
shape a worker serves. It deliberately carries no jax objects — the
JAX-free surfaces (rig meshworker role, race harness, orchestration
tests) use the same vocabulary the device path does. The jax-side
translation to ``parallel.sharding.MeshSpec``/``Mesh`` lives in
``placement.mesh_for_layout``.

The **tier label** is the orchestration hook: distinct mesh shapes are
distinct cost tiers in the placement walk (``orchestration/core.py``
keys costs by backend-URI substring), so a route that carries
``tier_label`` — e.g. ``/v1/detector-mesh-dp8`` — lets
``orchestration_costs="mesh-dp8=1,mesh-tp4=4"`` price a dp=8 small-model
endpoint against a tp=4 large-model endpoint in the cheapest-first walk.
"""

from __future__ import annotations

from dataclasses import dataclass

#: Serving meshes are declared over these axes, in this order. ``fsdp``
#: and ``ep`` stay runtime-internal (the low-level AI4E_RUNTIME_FSDP/EP
#: knobs) — a serving spec describes request placement, and requests ride
#: the batch (dp), feature (tp) and sequence (sp) dimensions.
AXES = ("dp", "tp", "sp")


class MeshSpecError(ValueError):
    """A mesh spec string or its device assignment is invalid — raised at
    registration/boot, never on the request path."""


@dataclass(frozen=True)
class MeshLayout:
    """A validated serving-mesh shape. ``dp`` shards the batch dimension,
    ``tp`` the feature dimensions (via partition rules), ``sp`` the
    sequence dimension (ring/Ulysses attention)."""

    dp: int = 1
    tp: int = 1
    sp: int = 1

    def __post_init__(self):
        for axis in AXES:
            v = getattr(self, axis)
            if not isinstance(v, int) or v < 1:
                raise MeshSpecError(
                    f"mesh axis {axis}={v!r} must be a positive int")

    @property
    def size(self) -> int:
        """Devices this layout occupies."""
        return self.dp * self.tp * self.sp

    @property
    def data_axis_multiple(self) -> int:
        """Every batch bucket must divide evenly over the batch axis —
        the SPMD rule ``ModelRuntime.register`` pads buckets to."""
        return self.dp

    @property
    def tier_label(self) -> str:
        """Stable substring identifying this shape as an orchestration
        cost tier (``"mesh-dp8"``, ``"mesh-tp4"``, ``"mesh-dp2tp2"``).
        Unit axes are elided; the trivial 1×1×1 layout is ``"mesh-dp1"``."""
        parts = [f"{axis}{getattr(self, axis)}"
                 for axis in AXES if getattr(self, axis) > 1]
        return "mesh-" + ("".join(parts) or "dp1")

    @classmethod
    def parse(cls, text: str) -> "MeshLayout":
        """Parse the spec grammar: comma-separated ``axis=N`` with axes
        from ``dp``/``tp``/``sp``, each at most once, N a positive int.
        Raises ``MeshSpecError`` with the offending token named."""
        seen: dict[str, int] = {}
        for token in text.split(","):
            token = token.strip()
            if not token:
                continue
            key, sep, value = token.partition("=")
            key = key.strip()
            if not sep or key not in AXES:
                raise MeshSpecError(
                    f"bad mesh spec token {token!r}: expected axis=N with "
                    f"axis in {'/'.join(AXES)}")
            if key in seen:
                raise MeshSpecError(f"mesh axis {key} given twice in {text!r}")
            try:
                n = int(value.strip())
            except ValueError:
                raise MeshSpecError(
                    f"mesh axis {key}={value.strip()!r} is not an int") from None
            seen[key] = n
        if not seen:
            raise MeshSpecError(f"empty mesh spec {text!r}")
        return cls(**seen)

    def validate(self, device_count: int, process_count: int = 1) -> None:
        """Device-assignment check, run at registration: the layout must
        cover exactly the visible devices, and on a multi-process mesh
        each process must hold an equal slice of them."""
        if self.size != device_count:
            raise MeshSpecError(
                f"mesh spec {self.describe()['spec']} needs {self.size} "
                f"devices, got {device_count} (CPU substrate: set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={self.size})")
        if process_count > 1 and device_count % process_count:
            raise MeshSpecError(
                f"{device_count} devices do not split evenly over "
                f"{process_count} processes")

    def describe(self) -> dict:
        """The ``GET /v1/models`` introspection entry."""
        spec = ",".join(f"{axis}={getattr(self, axis)}" for axis in AXES
                        if getattr(self, axis) > 1) or "dp=1"
        return {"spec": spec, "dp": self.dp, "tp": self.tp, "sp": self.sp,
                "devices": self.size, "tier": self.tier_label,
                "data_axis_multiple": self.data_axis_multiple}


def parse_mesh_spec(text: str | None) -> MeshLayout | None:
    """Config-surface entry point: ``None``/empty/``"off"`` means the mesh
    serving plane is off (the byte-identical default path)."""
    if text is None:
        return None
    text = text.strip()
    if not text or text.lower() == "off":
        return None
    return MeshLayout.parse(text)
