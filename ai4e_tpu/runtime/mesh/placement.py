"""Device placement for the mesh serving plane (docs/mesh_serving.md).

Three concerns, all thin layers over ``parallel/sharding.py``:

- **layout → mesh**: translate the declarative ``MeshLayout`` into the
  runtime's named ``jax.sharding.Mesh`` (dp/fsdp/ep/sp/tp axis order,
  ``make_mesh``'s tp-innermost ICI layout);
- **batch-axis placement**: the NamedSharding that puts a request batch's
  leading dimension on the data axes and replicates the rest — what the
  registry jits inputs against and ``h2d_resident`` places with;
- **partition rules**: resolve a regex rule set against a checkpoint
  param tree (first-match-wins, complete-by-construction — see
  ``spec_for_param``) so a registration error surfaces as a readable
  per-param report instead of a mid-placement ValueError.

``fetch_to_host`` is the blessed device→host transfer helper: the ONE
place in ``runtime/``+``parallel/`` allowed to call a bare
``jax.device_get`` (AIL014 ``unplaced-device-transfer`` exempts this
module). Outputs arrive replicated-or-single-device by construction
(``ModelRuntime`` jits outputs replicated on multi-process meshes), so
the fetch needs no placement argument — every OTHER device transfer on
the serving path must state where the data lives.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.sharding import MeshSpec, make_mesh
from .spec import MeshLayout

#: Mesh axes the batch (leading) dimension shards over — dp plus fsdp so
#: a serving mesh composes with an fsdp-split runtime mesh unchanged.
BATCH_AXES = ("dp", "fsdp")


def mesh_for_layout(layout: MeshLayout, devices=None) -> Mesh:
    """Build the named device mesh for a validated serving layout."""
    devices = devices if devices is not None else jax.devices()
    layout.validate(len(devices), jax.process_count())
    return make_mesh(MeshSpec(dp=layout.dp, tp=layout.tp, sp=layout.sp),
                     devices=devices)


def batch_axis_spec(ndim: int, batch_axis: int = 0) -> P:
    """PartitionSpec placing dimension ``batch_axis`` of a rank-``ndim``
    array on the data axes, everything else replicated."""
    if not 0 <= batch_axis < ndim:
        raise ValueError(f"batch_axis {batch_axis} out of range for "
                         f"rank-{ndim} input")
    axes: list = [None] * ndim
    axes[batch_axis] = BATCH_AXES
    return P(*axes)


def batch_placement(mesh: Mesh, ndim: int,
                    batch_axis: int = 0) -> NamedSharding:
    """The input/output sharding for request batches on ``mesh``."""
    return NamedSharding(mesh, batch_axis_spec(ndim, batch_axis))


def match_partition_rules(rules, params) -> dict[str, P]:
    """Resolve a regex rule set against a param tree WITHOUT placing it:
    ``{joined/param/path: PartitionSpec}`` for introspection and
    registration-time validation. Raises ``ValueError`` naming every
    unmatched non-scalar param at once (a checkpoint with three unmapped
    layers should fail with three names, not one per retry)."""
    from ...parallel.sharding import spec_for_param
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    resolved: dict[str, P] = {}
    missing: list[str] = []
    for path, leaf in flat:
        joined = "/".join(str(p.key if hasattr(p, "key") else p.idx)
                          for p in path)
        try:
            resolved[joined] = spec_for_param(
                tuple(p.key if hasattr(p, "key") else p.idx for p in path),
                leaf, rules)
        except ValueError:
            missing.append(joined)
    if missing:
        raise ValueError(
            f"partition rules leave {len(missing)} param(s) unmapped: "
            f"{', '.join(missing)} (add rules or a ('.*', P()) catch-all)")
    return resolved


def fetch_to_host(out):
    """Blessed device→host fetch for serving outputs (module docstring:
    the one sanctioned bare ``device_get`` in the serving tree)."""
    return jax.device_get(out)
