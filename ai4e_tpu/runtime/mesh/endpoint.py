"""MeshServable endpoint — the runtime facade a mesh worker serves through.

``MeshEndpoint`` slots into the existing ``MicroBatcher``/``ModelRuntime``
contract: the batcher and worker hold it where they held the runtime, and
every capability they probe for — fused ``run_batch_report``, phased
``run_batch_phases``, the split-phase h2d/execute/d2h surface PR 13's
double-buffering rides — delegates through, so the device path is
byte-identical to the unwrapped runtime when nothing degrades. What the
facade adds (docs/mesh_serving.md):

- **registration validation**: ``register_meshed`` checks the declared
  ``MeshLayout`` against the runtime's actual mesh and resolves the
  servable's regex partition rules against the real param tree before
  any placement happens — an unmapped tp param fails registration with
  every missing path named, never the request path;
- **poison accounting**: batch poison reports (real, from the multihost
  data plane; or injected via ``AI4E_FAULT_MESH_POISON_NTHS`` on the
  single-host CPU substrate) flow to the ``MeshCoordinator`` so repeated
  degradation flips the endpoint unhealthy;
- **per-process phase stamps**: the multihost runtime's per-process
  device phases drain through here for the batcher to stamp into each
  request's hop ledger (``h2d``/``execute`` with ``reason="proc=N"``).

Fault injection mirrors ``AI4E_FAULT_FETCH_FAIL_NTHS``: 1-based batch
ordinals (comma-separated) whose batch gets one poisoned row — empty in
production; the chaos suite drives the redelivery contract with it.
"""

from __future__ import annotations

import logging
import os

from .coordinator import MeshCoordinator
from .redelivery import EndpointHealth
from .spec import MeshLayout, MeshSpecError

log = logging.getLogger("ai4e_tpu.mesh")


def _fault_poison_nths() -> frozenset[int]:
    raw = os.environ.get("AI4E_FAULT_MESH_POISON_NTHS", "")
    return frozenset(int(s) for s in raw.split(",") if s.strip())


class MeshEndpoint:
    """Runtime facade binding a validated layout + health to a runtime
    (``ModelRuntime`` or ``MultihostRuntime``)."""

    def __init__(self, runtime, layout: MeshLayout,
                 health: EndpointHealth | None = None,
                 coordinator: MeshCoordinator | None = None):
        self._runtime = runtime
        self.layout = layout
        self.health = (health if health is not None
                       else getattr(coordinator, "health", None)
                       or EndpointHealth())
        self.coordinator = coordinator or MeshCoordinator(
            layout, health=self.health)
        self._validate_mesh()
        self._batch_count = 0  # fault-injection ordinal
        self._poison_nths = _fault_poison_nths()
        if self._poison_nths:
            log.warning("mesh fault injection armed: poisoning batches %s",
                        sorted(self._poison_nths))

    def _validate_mesh(self) -> None:
        """The declared serving layout must BE the runtime's mesh — a
        worker advertising dp=8 while executing on dp=4 would mis-pad
        buckets and mis-report its cost tier."""
        shape = dict(self._runtime.mesh.shape)
        actual = {"dp": shape.get("dp", 1) * shape.get("fsdp", 1),
                  "tp": shape.get("tp", 1), "sp": shape.get("sp", 1)}
        declared = {"dp": self.layout.dp, "tp": self.layout.tp,
                    "sp": self.layout.sp}
        if actual != declared:
            raise MeshSpecError(
                f"mesh layout {declared} does not match the runtime mesh "
                f"{actual} (mesh shape {shape})")

    def __getattr__(self, name: str):
        return getattr(self._runtime, name)

    # -- registration --------------------------------------------------------

    def register_meshed(self, servable, partition_rules=None):
        """Validate + register a servable on this mesh endpoint.

        ``partition_rules`` (or the servable's own
        ``param_sharding_rules``) in the regex form are resolved against
        the actual param tree FIRST (``placement.match_partition_rules``)
        so completeness errors carry every unmapped param path; the
        substring-dict form passes through unchanged. Delegates to the
        runtime's ``register`` for placement, bucket alignment to the
        data-axis multiple, and program compilation."""
        rules = (partition_rules if partition_rules is not None
                 else servable.param_sharding_rules)
        if isinstance(rules, (list, tuple)):
            from .placement import match_partition_rules
            match_partition_rules(rules, servable.params)
        if rules is not None:
            servable.param_sharding_rules = rules
        return self._runtime.register(servable)

    # -- introspection -------------------------------------------------------

    def describe(self) -> dict:
        out = dict(self.layout.describe())
        out.update({"healthy": self.health.healthy,
                    "process_count": self.coordinator.process_count})
        if not self.health.healthy:
            out["unhealthy_reason"] = self.health.reason
        return out

    # -- execution (poison injection + coordinator accounting) ---------------

    def _inject(self, rows: int, poisoned: frozenset) -> frozenset:
        """Apply fault injection and report the batch's poison outcome to
        the coordinator. Injected poison is attributed to a virtual
        follower (process 1) so the single-host CPU substrate exercises
        the same health state machine a real degraded follower drives;
        real multihost poison is reported by the ``poison_listener`` hook
        instead (``coordinator.attach``), not double-counted here."""
        self._batch_count += 1
        if self._batch_count in self._poison_nths:
            poisoned = frozenset(poisoned | {(self._batch_count - 1) % rows})
            log.warning("fault injection: poisoned row %d of batch %d",
                        (self._batch_count - 1) % rows, self._batch_count)
        if self._poison_nths:
            flags = [0, 1] if poisoned else [0, 0]
            self.coordinator.observe_poison(flags)
        return poisoned

    def run_batch_report(self, name: str, batch):
        runner = getattr(self._runtime, "run_batch_report", None)
        if runner is not None:
            out, poisoned = runner(name, batch)
        else:
            out, poisoned = self._runtime.run_batch(name, batch), frozenset()
        return out, self._inject(batch.shape[0], poisoned)

    def run_batch_phases(self, name: str, batch):
        phased = getattr(self._runtime, "run_batch_phases", None)
        if phased is not None:
            out, poisoned, phases = phased(name, batch)
        else:
            # MultihostRuntime has no phased surface (followers mirror
            # single fused calls) — same undecomposed fallback the
            # registry's own multi-process branch takes.
            out, poisoned = self._runtime.run_batch_report(name, batch)
            phases = {}
        return out, self._inject(batch.shape[0], poisoned), phases

    def supports_split_phases(self) -> bool:
        probe = getattr(self._runtime, "supports_split_phases", None)
        return bool(probe()) if probe is not None else False

    def drain_process_phases(self):
        drain = getattr(self._runtime, "drain_process_phases", None)
        return drain() if drain is not None else []
