"""Mesh serving plane — a worker *is* a mesh endpoint (docs/mesh_serving.md).

The package splits along the JAX boundary on purpose:

- ``spec`` and ``redelivery`` are stdlib-only, so the JAX-free surfaces
  that need the vocabulary — the batcher's poison contract, the race
  harness, the rig's meshworker role, the analyzer — import them without
  pulling a device runtime into the process;
- ``placement``, ``endpoint`` and ``coordinator`` hold the device-side
  machinery and import jax at module level; reach them via the lazy
  attributes below (or import the submodules directly).
"""

from .redelivery import EndpointHealth, RowPoisoned, redeliver_poisoned
from .spec import MeshLayout, MeshSpecError, parse_mesh_spec

_LAZY = {
    "MeshEndpoint": ".endpoint",
    "MeshCoordinator": ".coordinator",
}

__all__ = [
    "EndpointHealth",
    "MeshCoordinator",
    "MeshEndpoint",
    "MeshLayout",
    "MeshSpecError",
    "RowPoisoned",
    "parse_mesh_spec",
    "redeliver_poisoned",
]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib
    return getattr(importlib.import_module(mod, __name__), name)
