"""Poisoned-row contract + mesh-endpoint health — the JAX-free half of the
mesh serving plane's failure semantics (docs/mesh_serving.md).

A mesh batch can partially degrade: a follower process dies or fails its
shard fetch mid-batch and its rows execute on a zeros shard — any
"result" for those rows would be a confidently wrong answer. The
contract:

- the batcher fails exactly the poisoned rows' futures with
  ``RowPoisoned`` (the other rows complete normally);
- the worker's async path catches it and **redelivers the task** through
  ``redeliver_poisoned`` — a terminality probe followed by the same
  same-endpoint republish the BatcherSaturated path uses — instead of
  failing the task. A task whose record is already terminal (a duplicate
  delivery completed it concurrently) is NOT republished: never a
  duplicate client-visible completion.

This module is stdlib-only so the race harness (tests/
test_race_regressions.py, which runs in the JAX-free race-smoke CI job)
exercises the REAL redelivery code, not a model of it.
"""

from __future__ import annotations

import logging

log = logging.getLogger("ai4e_tpu.mesh")


class RowPoisoned(RuntimeError):
    """One row of a batch was invalidated by a degraded mesh host. The
    row's task must be redelivered, not completed and not terminally
    failed — subclassing RuntimeError keeps existing whole-batch failure
    handling working for callers that don't know about partial degrade."""

    def __init__(self, message: str = "result invalidated: a worker host "
                 "degraded while executing this row's shard"):
        super().__init__(message)


class EndpointHealth:
    """The mesh endpoint's admission health flag. Flipped unhealthy by the
    coordinator (follower death / repeated poisoned batches); read by the
    worker's admission check, which answers 500 so the dispatcher's
    breaker records a FAILURE and ejects the endpoint (a 503 would be
    saturation-neutral — see ``resilience/health.py.observe_status``:
    saturation means "peers are melting too", a dead follower means "this
    endpoint specifically cannot answer correctly")."""

    def __init__(self) -> None:
        self.healthy = True
        self.reason = ""

    def mark_unhealthy(self, reason: str) -> None:
        if self.healthy:
            log.error("mesh endpoint unhealthy: %s", reason)
        self.healthy = False
        self.reason = reason

    def mark_healthy(self) -> None:
        if not self.healthy:
            log.info("mesh endpoint recovered (was: %s)", self.reason)
        self.healthy = True
        self.reason = ""


async def redeliver_poisoned(task_manager, task_id: str,
                             fallback_endpoint: str) -> bool:
    """Hand a poisoned row's task back to the broker for redelivery.

    Probes the task record ONCE: a terminal record means a concurrent
    path (duplicate delivery, another replica) already finished the task
    — republishing would re-execute completed work and risk a duplicate
    client-visible completion, so the poison outcome is dropped in its
    favor. Otherwise the task is republished to its recorded endpoint
    (same-endpoint republish with empty body → original-body replay →
    redelivery, the BatcherSaturated idiom). Returns True when the task
    was republished.

    The probe and the republish are two store calls with a suspension
    between them — the republish itself is safe to race a concurrent
    completion because redelivery consumers suppress duplicates against
    the terminal record (``update_task_status_if``), which the
    interleaving regression in tests/test_race_regressions.py pins.
    """
    from ...taskstore.task import TaskStatus
    record = await task_manager.get_task_status(task_id)
    status = TaskStatus.canonical((record or {}).get("Status", ""))
    if status in TaskStatus.TERMINAL:
        log.info("poisoned row for task %s dropped: task already %s "
                 "(duplicate-suppressed)", task_id, status)
        return False
    endpoint = (record or {}).get("Endpoint") or fallback_endpoint
    await task_manager.add_pipeline_task(task_id, endpoint)
    log.warning("task %s redelivered to %s after a poisoned mesh row",
                task_id, endpoint)
    return True
