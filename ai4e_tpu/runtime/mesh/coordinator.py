"""Multi-process mesh boot + health (docs/mesh_serving.md).

Role split on a ``process_count > 1`` mesh (unchanged from the multihost
data plane): process 0 — the **primary** — serves HTTP and drives batch
execution; every other process — a **follower** — mirrors executions in
``MultihostRuntime.follower_loop``. What the coordinator adds is the
*health* half of that contract:

- every ``_gather_poison`` outcome flows through ``observe_poison``
  (the ``poison_listener`` hook on ``MultihostRuntime``): a process that
  poisons ``unhealthy_after`` consecutive batches is treated as dead —
  its rows keep poisoning every batch it should have computed, so
  continuing to admit traffic just burns redeliveries;
- a dead follower flips ``EndpointHealth`` unhealthy; the worker's
  admission check then answers 500, dispatcher breakers record failures,
  and the endpoint is ejected from routing (``resilience/health.py``) —
  in-flight poisoned rows are redelivered per-task by the worker
  (``redelivery.redeliver_poisoned``), so nothing is silently lost;
- one clean batch (no poison flags) marks the endpoint healthy again:
  a follower restart re-enters the SPMD loop and the first good gather
  is the recovery proof the half-open breaker probe will observe.

The coordinator is deliberately JAX-free (process identity is injected)
so the rig's meshworker role and the race harness drive the same state
machine the production worker runs.
"""

from __future__ import annotations

import logging

from .redelivery import EndpointHealth
from .spec import MeshLayout

log = logging.getLogger("ai4e_tpu.mesh")


class MeshCoordinator:
    """Follower-health bookkeeping for one mesh endpoint."""

    def __init__(self, layout: MeshLayout,
                 health: EndpointHealth | None = None,
                 process_count: int = 1, process_index: int = 0,
                 unhealthy_after: int = 3):
        if unhealthy_after < 1:
            raise ValueError("unhealthy_after must be >= 1")
        self.layout = layout
        self.health = health or EndpointHealth()
        self.process_count = process_count
        self.process_index = process_index
        self.unhealthy_after = unhealthy_after
        self._consecutive: dict[int, int] = {}

    @property
    def is_primary(self) -> bool:
        return self.process_index == 0

    def attach(self, multihost_runtime) -> None:
        """Subscribe to the multihost data plane's poison gathers."""
        multihost_runtime.poison_listener = self.observe_poison

    def observe_poison(self, flags) -> None:
        """One ``_gather_poison`` outcome: ``flags[proc]`` nonzero means
        that process poisoned its shard of this batch."""
        any_poison = False
        for proc, flag in enumerate(flags):
            if flag:
                any_poison = True
                n = self._consecutive.get(proc, 0) + 1
                self._consecutive[proc] = n
                if n >= self.unhealthy_after:
                    self.health.mark_unhealthy(
                        f"mesh process {proc} poisoned {n} consecutive "
                        f"batches (presumed dead)")
            else:
                self._consecutive[proc] = 0
        if not any_poison and not self.health.healthy:
            self.health.mark_healthy()

    def note_follower_death(self, proc: int, reason: str = "") -> None:
        """Out-of-band death signal (supervisor observed the process
        exit) — flips health immediately, no threshold."""
        self._consecutive[proc] = self.unhealthy_after
        self.health.mark_unhealthy(
            f"mesh process {proc} died{': ' + reason if reason else ''}")

    def describe(self) -> dict:
        return {"process_count": self.process_count,
                "process_index": self.process_index,
                "primary": self.is_primary,
                "healthy": self.health.healthy,
                "reason": self.health.reason,
                "unhealthy_after": self.unhealthy_after}
