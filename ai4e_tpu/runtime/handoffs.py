"""Pipeline handoff builders — stage-to-stage payload shaping.

The reference's ensembles replay the ORIGINAL request to every stage
(``CacheConnectorUpsert.cs:144-176``): its classifier re-reads the whole
camera-trap image. Real detector→classifier pipelines classify the
detector's CROPS — smaller payloads, and the classifier sees the animal,
not the scene. ``crops_handoff`` builds that stage: it receives the
detector's result AND its decoded input image (two-argument handoff
contract, ``InferenceWorker.serve_model``), crops each detection box,
resizes to the classifier's input, and ships the stack to the next stage's
*batch* endpoint as one npy payload.
"""

from __future__ import annotations

import io

import numpy as np


def crops_handoff(endpoint: str, crop_size: int = 224, max_crops: int = 16,
                  min_score: float | None = None):
    """Handoff callable ``(result, example) -> (endpoint, stack_bytes) | None``.

    - ``result``: the detector's postprocess output
      (``{"detections": [{"box": [y0,x0,y1,x1], "score", "class_id"}, ...]}``);
    - ``example``: the decoded input image (H, W, 3), uint8 or float [0,1];
    - crops are clamped to the image, padded to ≥1px, resized to
      ``(crop_size, crop_size)`` and stacked — ``None`` when nothing
      (above ``min_score``) was detected, so the stage completes the task.
    """
    def handoff(result, example):
        detections = (result or {}).get("detections") or []
        if min_score is not None:
            detections = [d for d in detections if d["score"] >= min_score]
        detections = detections[:max_crops]
        if not detections:
            return None

        from .families import cast_image_payload
        img = cast_image_payload(np.asarray(example), np.uint8)
        h, w = img.shape[:2]

        from PIL import Image
        crops = []
        for det in detections:
            y0, x0, y1, x1 = det["box"]
            y0 = int(np.clip(np.floor(y0), 0, h - 1))
            x0 = int(np.clip(np.floor(x0), 0, w - 1))
            y1 = int(np.clip(np.ceil(y1), y0 + 1, h))
            x1 = int(np.clip(np.ceil(x1), x0 + 1, w))
            crop = Image.fromarray(img[y0:y1, x0:x1])
            crop = crop.resize((crop_size, crop_size), Image.BILINEAR)
            crops.append(np.asarray(crop, np.uint8))
        buf = io.BytesIO()
        np.save(buf, np.stack(crops))
        return endpoint, buf.getvalue()

    return handoff
