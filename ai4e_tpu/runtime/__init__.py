from .batcher import BatcherSaturated, MicroBatcher
from .families import FAMILIES, build_servable
from .handoffs import crops_handoff
from .registry import ModelRuntime, ServableModel, enable_compilation_cache
from .worker import InferenceWorker

__all__ = [
    "BatcherSaturated",
    "FAMILIES",
    "MicroBatcher",
    "ModelRuntime",
    "ServableModel",
    "InferenceWorker",
    "build_servable",
    "crops_handoff",
    "enable_compilation_cache",
]
