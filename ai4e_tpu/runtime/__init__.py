from .batcher import BatcherSaturated, MicroBatcher
from .families import FAMILIES, build_servable
from .registry import ModelRuntime, ServableModel, enable_compilation_cache
from .worker import InferenceWorker

__all__ = [
    "BatcherSaturated",
    "FAMILIES",
    "MicroBatcher",
    "ModelRuntime",
    "ServableModel",
    "InferenceWorker",
    "build_servable",
    "enable_compilation_cache",
]
