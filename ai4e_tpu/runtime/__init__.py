"""Runtime package — lazy exports (PEP 562).

The decode engine (``runtime/decode.py``) is deliberately importable
without JAX or numpy: the race-smoke CI job explores its slot-
conservation invariants with no accelerator toolchain installed. Eager
re-exports here would drag ``registry``/``batcher`` (and therefore JAX)
into every ``ai4e_tpu.runtime.*`` import, so the package resolves its
public names on first attribute access instead.
"""

import importlib

_EXPORTS = {
    "BatcherSaturated": ".batcher",
    "MicroBatcher": ".batcher",
    "FAMILIES": ".families",
    "build_servable": ".families",
    "crops_handoff": ".handoffs",
    "LadderManager": ".ladder",
    "ShapeHistogram": ".ladder",
    "derive_ladder": ".ladder",
    "ModelRuntime": ".registry",
    "ServableModel": ".registry",
    "enable_compilation_cache": ".registry",
    "InferenceWorker": ".worker",
    "DecodeEngine": ".decode",
    "DecodeSaturated": ".decode",
    "SlotPool": ".decode",
    "LMServable": ".kvcache",
    "PagedDecodeRuntime": ".kvcache",
    "build_lm_servable": ".kvcache",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        module = importlib.import_module(_EXPORTS[name], __name__)
        value = getattr(module, name)
        globals()[name] = value  # cache: later accesses skip this hook
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
