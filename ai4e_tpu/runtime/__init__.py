from .batcher import BatcherSaturated, MicroBatcher
from .families import FAMILIES, build_servable
from .handoffs import crops_handoff
from .ladder import LadderManager, ShapeHistogram, derive_ladder
from .registry import ModelRuntime, ServableModel, enable_compilation_cache
from .worker import InferenceWorker

__all__ = [
    "BatcherSaturated",
    "FAMILIES",
    "LadderManager",
    "MicroBatcher",
    "ModelRuntime",
    "ServableModel",
    "ShapeHistogram",
    "InferenceWorker",
    "build_servable",
    "crops_handoff",
    "derive_ladder",
    "enable_compilation_cache",
]
