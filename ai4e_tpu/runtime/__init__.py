from .batcher import BatcherSaturated, MicroBatcher
from .registry import ModelRuntime, ServableModel, enable_compilation_cache
from .worker import InferenceWorker

__all__ = [
    "BatcherSaturated",
    "MicroBatcher",
    "ModelRuntime",
    "ServableModel",
    "InferenceWorker",
    "enable_compilation_cache",
]
