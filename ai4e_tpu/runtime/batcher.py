"""Micro-batcher — packs queued requests into dense fixed-shape TPU batches.

THE architectural divergence from the reference (SURVEY.md §7 hard parts #1):
the reference dispatches one task per HTTP POST to a GPU container; a TPU mesh
wants large dense batches. The batcher sits between the request path and the
device:

- requests arrive one at a time (``submit`` returns a future);
- a flusher drains the pending queue whenever the device is free, taking up to
  ``max_bucket`` examples — under load the batch grows toward the biggest
  bucket (adaptive batching), idle requests leave at batch 1 with
  ``max_wait_ms`` bounding added latency;
- the batch is padded to the smallest compiled bucket (no recompiles, static
  shapes) and run on the mesh via a single executor thread (one TPU program
  at a time — the device is the serial resource);
- outputs fan back out to per-request futures; per-example postprocess errors
  fail only that request (failure isolation: one bad image fails one task,
  never the batch).

Backpressure: ``pending_count`` over ``max_pending`` → ``submit`` raises
``BatcherSaturated`` and the service returns 503, which the dispatcher already
treats as backpressure — the queue-depth-vs-device-utilisation translation of
the reference's per-replica thread cap (SURVEY.md §7 hard part #2).
"""

from __future__ import annotations

import asyncio
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..rollout.drain import DrainingError, retire_pending
from .ladder import EXPOSITION_BUCKETS, exposition_buckets
from .registry import ModelRuntime

log = logging.getLogger("ai4e_tpu.batcher")


class BatcherSaturated(RuntimeError):
    pass


@dataclass
class _Pending:
    example: np.ndarray
    future: asyncio.Future
    enqueued: float = field(default_factory=time.perf_counter)
    priority: int = 0  # 0 = interactive, higher = background
    # Absolute wall-clock deadline (unix seconds; 0.0 = none): an entry
    # still pending when it passes is dropped at batch-cut time with
    # DeadlineExceeded instead of being padded onto the device
    # (admission/ — dead work never reaches the TPU).
    deadline_at: float = 0.0
    # Hop-ledger buffer (observability/ledger.HopLedger) the worker
    # passed with the request; the batcher stamps batch-cut and device
    # phases into it. None = no stamping (the default).
    ledger: object = None


class MicroBatcher:
    def __init__(
        self,
        runtime: ModelRuntime,
        max_wait_ms: float = 5.0,
        max_pending: int = 256,
        metrics: MetricsRegistry | None = None,
        pipeline_depth: int = 2,
        interactive_reserve: float = 0.25,
        priority_aging_s: float = 2.0,
        measure_phases: bool = False,
        ladder_manager=None,
        double_buffer: bool = False,
    ):
        self.runtime = runtime
        self.max_wait = max_wait_ms / 1000.0
        self.max_pending = max_pending
        # Priority isolation is enforced at BOTH gates:
        # - admission: background submits saturate at (1 - reserve) of the
        #   queue, so stacks can never eat the whole cap and 503 interactive
        #   traffic out of the batcher;
        # - batch cut: interactive-first, but a background item's effective
        #   priority decays by 1 class per ``priority_aging_s`` waited, so
        #   sustained interactive load delays stacks boundedly instead of
        #   starving them (0 disables aging → strict priority).
        self._background_cap = max(1, int(max_pending
                                          * (1.0 - interactive_reserve)))
        self.priority_aging_s = priority_aging_s
        self.metrics = metrics or DEFAULT_REGISTRY
        self._pending: dict[str, list[_Pending]] = {}
        self._wakeup: asyncio.Event = asyncio.Event()
        self._stop = False
        # Rollout drain (rollout/drain.py, docs/deployment.md#drain):
        # while draining, submits raise DrainingError (the worker answers
        # 503 + Retry-After + X-Draining and async tasks redeliver through
        # the broker), the flusher stops cutting new batches, and batches
        # already on the device finish normally.
        self._draining = False
        self._flusher: asyncio.Task | None = None
        # ``pipeline_depth`` device-feeding threads + an equal-slot window:
        # the device still serialises compute, but batch N+1's host work
        # (padding, dispatch, result transfer) overlaps batch N's device time
        # instead of waiting on its device_get. Depth 2 (double buffering) is
        # right for a locally-attached chip; a remote-attached TPU whose
        # host↔device link is long-fat (the axon tunnel: ~70 ms RTT) needs
        # more in-flight batches to fill the pipe — depth 6 measured 2.5×
        # the sustained tiles/s of depth 2 there.
        if pipeline_depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1, got {pipeline_depth}")
        self.pipeline_depth = pipeline_depth
        self._executor = ThreadPoolExecutor(max_workers=pipeline_depth,
                                            thread_name_prefix="tpu-batcher")
        self._window = asyncio.Semaphore(pipeline_depth)
        self._inflight_execs: set[asyncio.Task] = set()
        # Traffic-tuned ladders (runtime/ladder.py, AI4E_RUNTIME_LADDER_
        # DERIVE): the manager sees every batch cut and re-derives each
        # servable's bucket ladder in the background. None (default) =
        # static factory ladders, no observation overhead.
        self._ladders = ladder_manager
        # With derivation on, the ai4e_batch_size exposition buckets are
        # built from the servables' OWN ladders at construction (the
        # static copy would drift the moment ladders are derived); with
        # it off they stay the static exposition ladder so the default
        # /metrics content is byte-identical to the pre-derivation
        # platform. Register AFTER all models so the union is complete.
        expo = (exposition_buckets(runtime.models.values())
                if ladder_manager is not None else EXPOSITION_BUCKETS)
        self._batch_size_hist = self.metrics.histogram(
            "ai4e_batch_size", "Executed batch sizes",
            buckets=(*expo, float("inf")))
        self._batch_latency = self.metrics.histogram(
            "ai4e_batch_exec_seconds", "Device execution time per batch")
        self._queue_wait = self.metrics.histogram(
            "ai4e_batch_queue_wait_seconds", "Request wait before batching")
        self._pending_gauge = self.metrics.gauge(
            "ai4e_batcher_pending", "Requests waiting for a batch slot")
        self._inflight_gauge = self.metrics.gauge(
            "ai4e_batcher_inflight_batches",
            "Device batches currently in the pipeline window")
        # Link accounting (VERDICT r2 #3): actual bytes shipped host→device
        # per executed batch (bucket-padded input) and device→host (fetched
        # outputs) — the numbers that bound throughput on a remote-attached
        # TPU, reported per-request by the bench.
        self._h2d_bytes = self.metrics.counter(
            "ai4e_batch_h2d_bytes_total",
            "Host-to-device bytes shipped (padded batches)")
        self._d2h_bytes = self.metrics.counter(
            "ai4e_batch_d2h_bytes_total",
            "Device-to-host bytes fetched (batch outputs)")
        # Deadline drops at the batch cut (admission/): same series every
        # other hop reports into, labeled with THIS hop.
        self._expired_total = self.metrics.counter(
            "ai4e_admission_expired_total",
            "Requests dropped on deadline expiry, by hop/priority")
        # Device-phase decomposition (observability/, ROADMAP item 2's
        # overlap metric): off by default — the batch path and /metrics
        # content are byte-identical until AI4E_OBSERVABILITY_HOP_LEDGER
        # turns it on. When on, batches run through the runtime's
        # run_batch_phases (measured h2d / compile-or-execute / d2h),
        # each phase lands in its histogram, and the h2d seconds spent
        # while ANOTHER batch was executing accumulate into the overlap
        # counter — overlap ratio ≈ how well transfers hide under
        # compute (1.0 = fully hidden, the double-buffering goal).
        self.measure_phases = measure_phases
        if measure_phases:
            import threading
            self._phase_hist = self.metrics.histogram(
                "ai4e_device_phase_seconds",
                "Device-boundary phase durations (h2d/compile/execute/"
                "d2h) per batch")
            self._overlap_total = self.metrics.counter(
                "ai4e_batch_h2d_overlap_seconds_total",
                "H2D transfer seconds that overlapped another batch's "
                "execute phase")
            self._overlap_ratio = self.metrics.gauge(
                "ai4e_batch_overlap_ratio",
                "Cumulative h2d/execute overlap ratio (overlapped h2d "
                "seconds / total h2d seconds)")
            self._phase_lock = threading.Lock()
            # Completed execute windows (start, end) + in-flight batch
            # starts — the overlap denominator's counterparty. In-flight
            # windows are approximated from the batch's call start (the
            # exact execute start is known only at completion), which
            # slightly over-counts overlap; documented in
            # docs/observability.md.
            from collections import deque as _deque
            self._exec_windows = _deque(maxlen=64)
            self._exec_pending: dict[int, float] = {}
            self._h2d_seconds = 0.0
            self._h2d_overlap_seconds = 0.0
        # Pad-waste accounting (ai4e_batch_pad_ratio / _pad_bytes_total):
        # the measurement that justifies — and regression-guards — ladder
        # derivation (docs/METRICS.md). Gated with the device-phase /
        # ladder instruments so the default batcher's /metrics stays
        # byte-identical to the pre-ladder platform.
        self._pad_enabled = measure_phases or ladder_manager is not None
        if self._pad_enabled:
            self._pad_state: dict[str, list[int]] = {}
            self._pad_ratio = self.metrics.gauge(
                "ai4e_batch_pad_ratio",
                "Cumulative padded-slots / occupied-slots per model "
                "(0 = every executed batch exactly filled its bucket)")
            self._pad_bytes = self.metrics.counter(
                "ai4e_batch_pad_bytes_total",
                "Host-to-device bytes spent on bucket padding, per model")
        # Double-buffered transfer pipeline (docs/device_path.md#double-
        # buffered-transfers, AI4E_RUNTIME_BATCH_DOUBLE_BUFFER): h2d,
        # execute, and d2h run on separate single-thread executors with
        # an alternating host staging-buffer ring, so batch N+1's
        # device_put overlaps batch N's execute and batch N's device_get
        # overlaps batch N+1's execute — the PR 8 overlap ratio's reason
        # to be > 0. Requires a runtime exposing the split-phase surface
        # (single-host ModelRuntime); otherwise the fused path serves.
        self._double = bool(
            double_buffer
            and getattr(runtime, "supports_split_phases", None) is not None
            and runtime.supports_split_phases())
        if self._double:
            self._h2d_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-h2d")
            self._exec_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-exec")
            self._d2h_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpu-d2h")
            # Host staging ring per (model, bucket): pipeline_depth
            # buffers cycling, so batch N+1 pads into a fresh buffer
            # while batch N's is still device-bound; the window
            # semaphore bounds in-flight batches at pipeline_depth, so
            # a buffer is never reused before its h2d completed.
            self._staging: dict[tuple[str, int], list] = {}
            self._staging_idx: dict[tuple[str, int], int] = {}

    # -- request side ------------------------------------------------------

    @property
    def pending_count(self) -> int:
        return sum(len(v) for v in self._pending.values())

    async def submit(self, model_name: str, example: np.ndarray,
                     priority: int = 0, deadline_at: float = 0.0,
                     ledger=None):
        """Queue one example; resolves to that example's postprocessed result.

        ``priority`` 0 is interactive (default); higher values are
        background classes (the batch API submits at 1). Every device batch
        is filled interactive-first, so a long background stack shares the
        device without queueing ahead of interactive latency — the
        isolation the reference gets only from separate container pools.

        ``deadline_at`` (absolute unix seconds; 0.0 = none): if the entry
        is still pending when the deadline passes, the await raises
        ``DeadlineExceeded`` at the next batch cut and the example never
        ships to the device (admission/).

        ``ledger`` (optional ``observability.ledger.HopLedger``): the
        batch cut and the device phases this example rides are stamped
        into it (``batched``/``h2d``/``execute``/``d2h``) — the worker
        flushes the buffer to the task store when the request finishes.
        """
        if self._stop:
            raise RuntimeError("batcher stopped")
        if self._draining:
            raise DrainingError("batcher draining; submit refused")
        cap = self.max_pending if priority <= 0 else self._background_cap
        if self.pending_count >= cap:
            raise BatcherSaturated(
                f"batcher at {self.pending_count}/{cap} pending "
                f"(priority {priority})")
        servable = self.runtime.models[model_name]
        expected = tuple(servable.input_shape)
        if tuple(example.shape) != expected:
            raise ValueError(
                f"bad input shape {example.shape}, expected {expected}")
        fut = asyncio.get_running_loop().create_future()
        self._pending.setdefault(model_name, []).append(
            _Pending(example, fut, priority=priority,
                     deadline_at=deadline_at, ledger=ledger))
        self._pending_gauge.set(self.pending_count)
        self._wakeup.set()
        return await fut

    # -- drain (rollout/drain.py drives these; docs/deployment.md) ---------

    def begin_drain(self) -> int:
        """Stop cutting new batches and retire every UNCUT pending entry
        with ``DrainingError`` (each redelivers through the broker per
        task). The take-and-clear is one synchronous step with the
        draining flip — no await — so a concurrently scheduled batch cut
        can never deliver into a future this sweep already failed
        (tests/test_race_regressions.py). Batches already in the pipeline
        window finish normally; ``drain_complete`` turns true when they
        have."""
        self._draining = True
        retired = retire_pending(self._pending)
        self._pending_gauge.set(self.pending_count)
        self._wakeup.set()
        return retired

    @property
    def drain_complete(self) -> bool:
        """Draining AND quiesced: nothing pending, nothing on the device."""
        return (self._draining and not self._inflight_execs
                and self.pending_count == 0)

    def resume_from_drain(self) -> None:
        """Re-arm after an aborted drain (the rollback path re-weights a
        worker back into service without a process restart)."""
        self._draining = False
        self._wakeup.set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._stop = False
        self._flusher = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stop = True
        self._wakeup.set()
        if self._flusher is not None:
            await self._flusher
        if self._inflight_execs:
            await asyncio.gather(*self._inflight_execs,
                                 return_exceptions=True)
        self._executor.shutdown(wait=True)
        if self._double:
            for pool in (self._h2d_pool, self._exec_pool, self._d2h_pool):
                pool.shutdown(wait=True)

    # -- flusher -----------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stop:
            if self.pending_count == 0:
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    continue
            # Brief PER-MODEL accumulation window: a model is cut when
            # ITS OWN largest bucket is full or ITS OWN oldest entry has
            # waited max_wait; until some model is ready, sleep to the
            # nearest per-model deadline. (The old global gate anchored
            # one shared window on the oldest pending anywhere and
            # compared the longest queue against the GLOBALLY largest
            # bucket — one model's ladder deciding another's cut, the
            # cross-model coupling per-model derived ladders cannot
            # tolerate.)
            if self.max_wait > 0:
                sleep_for = self._nearest_cut_deadline(time.perf_counter())
                if sleep_for is not None and sleep_for > 0:
                    await asyncio.sleep(sleep_for)
            now = time.perf_counter()
            if self._draining:
                # Drained pending queues are already empty; anything that
                # raced in between the retire sweep and the submit-side
                # refusal is retired here rather than cut to the device.
                retire_pending(self._pending)
                self._pending_gauge.set(self.pending_count)
                continue
            for model_name in list(self._pending):
                if not self._pending.get(model_name):
                    continue
                if not self._cut_ready(model_name, now):
                    continue  # still accumulating its own window
                # Acquire the window slot BEFORE carving the batch: while all
                # slots are busy, arriving requests keep joining the pending
                # queue, so the batch cut the moment a slot frees is as full
                # as possible (cutting first would freeze the batch at
                # whatever had arrived, then let it stale-wait).
                await self._window.acquire()
                batch, bucket = self._take_batch(model_name)
                if not batch:
                    self._window.release()
                    continue
                # Bounded pipelining: admit the batch and keep draining —
                # don't wait for its results.
                task = loop.create_task(
                    self._execute(loop, model_name, batch, bucket))
                self._inflight_execs.add(task)
                self._inflight_gauge.set(len(self._inflight_execs))

                def _done(t: asyncio.Task) -> None:
                    self._inflight_execs.discard(t)
                    self._inflight_gauge.set(len(self._inflight_execs))
                    self._window.release()

                task.add_done_callback(_done)

    def _cut_ready(self, model_name: str, now: float) -> bool:
        """This model's cut decision, against ITS OWN ladder only: full
        largest bucket, or its oldest pending entry has waited out the
        accumulation window (max_wait == 0 is always ready)."""
        queue = self._pending.get(model_name)
        if not queue:
            return False
        servable = self.runtime.models.get(model_name)
        if servable is not None and len(queue) >= servable.max_bucket:
            return True
        return (self.max_wait <= 0
                or now - queue[0].enqueued >= self.max_wait)

    def _nearest_cut_deadline(self, now: float) -> float | None:
        """Seconds until the FIRST model becomes cut-ready: 0.0 when one
        already is (full bucket or expired window), the smallest
        remaining per-model window otherwise, None with nothing
        pending."""
        nearest: float | None = None
        for name, queue in self._pending.items():
            if not queue:
                continue
            if self._cut_ready(name, now):
                return 0.0
            remaining = self.max_wait - (now - queue[0].enqueued)
            nearest = (remaining if nearest is None
                       else min(nearest, remaining))
        return nearest

    def _take_batch(self, model_name: str
                    ) -> tuple[list[_Pending], int]:
        """Cut one batch and choose its bucket from ONE snapshot of the
        servable's ladder. Returns ``(batch, bucket)`` — the bucket is
        decided HERE, not in ``_execute``: a deriver-thread ladder swap
        between the cut and the execute would otherwise let
        ``bucket_for(n)`` clamp to a new, smaller top bucket than the
        cut itself (IndexError mid-padding, every future in the batch
        stranded). A bucket chosen from the pre-swap tuple stays safe on
        either side of a swap — old-ladder programs are never evicted
        (``_executed_shapes`` is append-only)."""
        queue = self._pending.get(model_name, [])
        if not queue:
            return [], 0
        queue = self._sweep_expired(model_name, queue)
        if not queue:
            return [], 0
        servable = self.runtime.models[model_name]
        ladder = tuple(servable.batch_buckets)  # single read vs the swap
        if self._ladders is not None:
            # Feed the PRE-clamp demand to the ladder deriver — O(1)
            # histogram update; derivation/compile runs on its own
            # thread. Observing the post-clamp cut size would let the
            # ladder only ever ratchet DOWN: once a swap shrinks the top
            # bucket, every cut is capped at it and the histogram could
            # never witness the larger demand that should grow the
            # ladder back (the manager clamps to the FACTORY ladder's
            # max — the operator's memory bound).
            self._ladders.observe_cut(model_name, len(queue))
        take = min(len(queue), ladder[-1])
        if take < len(queue):
            # Cut interactive-first: a background stack never queues ahead
            # of fresh interactive requests when the batch can't hold
            # everyone — but waiting decays a class per priority_aging_s so
            # nothing starves. Within a class the aged key preserves
            # oldest-first. Full drains skip the sort.
            now = time.perf_counter()
            aging = self.priority_aging_s

            def effective(p: _Pending) -> float:
                if aging <= 0:
                    return float(p.priority)
                return p.priority - (now - p.enqueued) / aging

            queue = sorted(queue, key=effective)
        batch, rest = queue[:take], queue[take:]
        self._pending[model_name] = rest
        self._pending_gauge.set(self.pending_count)
        bucket = next((b for b in ladder if b >= take), ladder[-1])
        return batch, bucket

    def _sweep_expired(self, model_name: str,
                       queue: list[_Pending]) -> list[_Pending]:
        """Drop pending entries whose deadline passed while they queued —
        at the batch cut, the last gate before the device (admission/: zero
        expired examples ever reach ``_execute``). Their futures resolve to
        ``DeadlineExceeded`` so the worker can move the task to the
        terminal ``expired`` status. Deadline-free entries pass untouched;
        the all-deadline-free fast path allocates nothing."""
        now = time.time()
        if not any(p.deadline_at and p.deadline_at <= now for p in queue):
            return queue
        from ..admission.deadline import DeadlineExceeded, priority_name
        live: list[_Pending] = []
        for p in queue:
            if (p.deadline_at and p.deadline_at <= now
                    and not p.future.done()):
                p.future.set_exception(
                    DeadlineExceeded("batcher", p.deadline_at))
                self._expired_total.inc(hop="batcher",
                                        priority=priority_name(p.priority))
            else:
                live.append(p)
        self._pending[model_name] = live
        self._pending_gauge.set(self.pending_count)
        return live

    def _note_phases(self, model_name: str, t_call: float,
                     phases: dict, batch: list[_Pending]) -> None:
        """Account one FUSED-path phased batch (``run_batch_phases``
        measures durations, not wall windows): reconstruct back-to-back
        windows from the call start and delegate. The double-buffered
        path calls ``_note_phase_windows`` directly with the real,
        possibly gapped, per-stage windows."""
        windows: dict[str, tuple[float, float]] = {}
        cursor = t_call
        for phase in ("h2d", "compile", "execute", "d2h"):
            dur = phases.get(phase)
            if dur is None:
                continue
            windows[phase] = (cursor, cursor + dur)
            cursor += dur
        self._note_phase_windows(model_name, windows, batch,
                                 token=id(batch))

    def _note_phase_windows(self, model_name: str,
                            windows: dict[str, tuple[float, float]],
                            batch: list[_Pending],
                            token: int | None = None) -> None:
        """Account one batch's measured phase wall windows (perf-counter
        space): phase histograms, h2d/execute overlap against OTHER
        batches' execute windows, and per-request ledger stamps.
        ``token`` identifies this batch in ``_exec_pending`` so its own
        in-flight execute never counts as overlap."""
        now = time.perf_counter()
        for phase, (w0, w1) in windows.items():
            self._phase_hist.observe(w1 - w0, phase=phase, model=model_name)
        h2d_w = windows.get("h2d")
        exec_w = windows.get("execute", windows.get("compile"))
        if h2d_w is not None and h2d_w[1] > h2d_w[0]:
            h2d = h2d_w[1] - h2d_w[0]
            with self._phase_lock:
                overlap = 0.0
                for w0, w1 in self._exec_windows:
                    overlap += max(0.0, min(h2d_w[1], w1) - max(h2d_w[0], w0))
                for tok, start in self._exec_pending.items():
                    if tok != token:
                        # In-flight batch: execute window approximated
                        # from its call start to now (over-counts by its
                        # own h2d time on the fused path; exact on the
                        # double-buffered path, whose pending entries
                        # are stamped at execute-stage entry — see
                        # __init__ comment / docs/observability.md).
                        overlap += max(0.0, min(h2d_w[1], now)
                                       - max(h2d_w[0], start))
                overlap = min(overlap, h2d)
                if exec_w is not None:
                    self._exec_windows.append(exec_w)
                self._h2d_seconds += h2d
                self._h2d_overlap_seconds += overlap
                ratio = (self._h2d_overlap_seconds / self._h2d_seconds
                         if self._h2d_seconds > 0 else 0.0)
            self._overlap_total.inc(overlap, model=model_name)
            self._overlap_ratio.set(ratio)
        elif exec_w is not None:
            with self._phase_lock:
                self._exec_windows.append(exec_w)
        # Ledger stamps ride wall-clock time like every other hop:
        # convert the perf-counter anchors through "now".
        stamped = [p for p in batch if p.ledger is not None]
        if stamped:
            epoch_off = time.time() - now
            for phase in ("h2d", "compile", "execute", "d2h"):
                w = windows.get(phase)
                if w is None:
                    continue
                for p in stamped:
                    p.ledger.stamp(phase, "device", t=epoch_off + w[0],
                                   ms=(w[1] - w[0]) * 1e3)

    def _note_pad(self, model_name: str, n: int, bucket: int,
                  example_nbytes: int) -> None:
        """Pad-waste accounting at the cut: cumulative padded/occupied
        slot ratio and padding bytes shipped to the device — the series
        that justifies (and regression-guards) ladder derivation."""
        if not self._pad_enabled:
            return
        state = self._pad_state.setdefault(model_name, [0, 0])
        state[0] += bucket - n
        state[1] += n
        self._pad_ratio.set(state[0] / state[1], model=model_name)
        if bucket > n:
            self._pad_bytes.inc((bucket - n) * example_nbytes,
                                model=model_name)

    def _staging_buffer(self, model_name: str, bucket: int,
                        servable) -> np.ndarray:
        """Next host staging buffer from the (model, bucket) ring — the
        alternating buffer pair (``pipeline_depth`` deep) that lets
        batch N+1 pad while batch N's buffer is still transfer-bound.
        The window semaphore admits at most ``pipeline_depth`` in-flight
        batches in FIFO order, so a buffer is never handed out again
        before its previous batch fully completed."""
        key = (model_name, bucket)
        # A ladder swap retired buckets: drop their rings, or shifting
        # traffic accumulates pipeline_depth full-size host buffers per
        # stale bucket forever (a 512px detector ring is ~200 MB each).
        # Swept on EVERY call — a shrink-only swap never allocates a new
        # key, so allocation-time-only eviction would keep the retired
        # larger ring for the process lifetime. In-flight batches hold
        # their own references to the arrays, so eviction only releases
        # this cache; a cut still riding the pre-swap ladder (this
        # call's ``bucket`` is exempt from the sweep) re-allocates.
        live = set(servable.batch_buckets)
        for stale in [k for k in self._staging
                      if k[0] == model_name and k[1] not in live
                      and k[1] != bucket]:
            del self._staging[stale]
            self._staging_idx.pop(stale, None)
        ring = self._staging.get(key)
        if ring is None:
            ring = [np.zeros((bucket, *servable.input_shape),
                             servable.input_dtype)
                    for _ in range(self.pipeline_depth)]
            self._staging[key] = ring
            self._staging_idx[key] = 0
        idx = self._staging_idx[key]
        self._staging_idx[key] = (idx + 1) % len(ring)
        return ring[idx]

    async def _execute(self, loop, model_name: str, batch: list[_Pending],
                       bucket: int) -> None:
        """Run one cut batch padded to ``bucket`` — chosen at cut time
        from the same ladder snapshot as the cut itself (see
        ``_take_batch``); never re-derived here."""
        servable = self.runtime.models[model_name]
        n = len(batch)
        now = time.perf_counter()
        for p in batch:
            self._queue_wait.observe(now - p.enqueued, model=model_name)

        if self._double:
            await self._execute_pipelined(loop, model_name, servable,
                                          batch, n, bucket)
            return

        padded = np.zeros((bucket, *servable.input_shape),
                          servable.input_dtype)
        for i, p in enumerate(batch):
            padded[i] = p.example
            if p.ledger is not None:
                p.ledger.stamp("batched", "batcher",
                               reason=f"size {n} bucket {bucket}")
        self._note_pad(model_name, n, bucket, padded.nbytes // bucket)

        t0 = time.perf_counter()
        # Phase-decomposed path (observability): measured h2d / execute /
        # d2h plus transfer/execute overlap accounting. Falls back to
        # run_batch_report — which surfaces rows a degraded follower
        # invalidated (multihost zeros-shard path) — and plain run_batch
        # for duck-typed runtimes without either.
        phased = (self.measure_phases
                  and getattr(self.runtime, "run_batch_phases", None)
                  is not None)
        runner = getattr(self.runtime, "run_batch_report", None)
        phases: dict = {}
        if phased:
            with self._phase_lock:
                self._exec_pending[id(batch)] = t0
        try:
            if phased:
                outputs, poisoned, phases = await loop.run_in_executor(
                    self._executor, self.runtime.run_batch_phases,
                    model_name, padded)
            elif runner is not None:
                outputs, poisoned = await loop.run_in_executor(
                    self._executor, runner, model_name, padded)
            else:
                outputs = await loop.run_in_executor(
                    self._executor, self.runtime.run_batch, model_name, padded)
                poisoned = frozenset()
        except Exception as exc:  # noqa: BLE001 — device failure fails the batch
            log.exception("batch execution failed for %s", model_name)
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(exc)
            return
        finally:
            if phased:
                with self._phase_lock:
                    self._exec_pending.pop(id(batch), None)
        if phases:
            self._note_phases(model_name, t0, phases, batch)
        # Mesh serving plane: per-mesh-process device phases (primary's
        # per-follower shard staging + the SPMD execute) stamped into each
        # request's ledger keyed by process index — existing h2d/execute
        # vocabulary, reason carries the key (docs/mesh_serving.md).
        drain = getattr(self.runtime, "drain_process_phases", None)
        if drain is not None:
            for label, proc, dur in drain():
                for p in batch:
                    if p.ledger is not None:
                        p.ledger.stamp(label, "device",
                                       reason=f"proc={proc}", ms=dur * 1e3)
        self._batch_latency.observe(time.perf_counter() - t0, model=model_name)
        self._batch_size_hist.observe(n, model=model_name)
        self._h2d_bytes.inc(padded.nbytes, model=model_name)
        self._d2h_bytes.inc(_tree_nbytes(outputs), model=model_name)
        await self._deliver(loop, model_name, servable, batch, outputs,
                            n, poisoned)

    async def _execute_pipelined(self, loop, model_name: str, servable,
                                 batch: list[_Pending], n: int,
                                 bucket: int) -> None:
        """The double-buffered execute path: padding into an alternating
        staging buffer, then h2d → execute → d2h on three dedicated
        single-thread executors. The device still serialises compute
        (one execute thread), but batch N+1's ``device_put`` runs while
        batch N executes and batch N's ``device_get`` runs while batch
        N+1 executes — transfer hidden under compute, measured by the
        phase windows this path hands ``_note_phase_windows`` verbatim
        (real wall windows, not back-to-back reconstructions)."""
        buf = self._staging_buffer(model_name, bucket, servable)
        for i, p in enumerate(batch):
            buf[i] = p.example
            if p.ledger is not None:
                p.ledger.stamp("batched", "batcher",
                               reason=f"size {n} bucket {bucket}")
        if n < bucket:
            buf[n:] = 0  # previous batch's rows must not ride as padding
        self._note_pad(model_name, n, bucket, buf.nbytes // bucket)
        token = id(batch)
        t0 = time.perf_counter()
        try:
            device_batch, h2d_w = await loop.run_in_executor(
                self._h2d_pool, self.runtime.h2d_resident, model_name, buf)
            if self.measure_phases:
                # Visible to concurrent batches' overlap accounting from
                # the moment this batch enters the execute stage.
                with self._phase_lock:
                    self._exec_pending[token] = time.perf_counter()
            try:
                out, label, exec_w = await loop.run_in_executor(
                    self._exec_pool, self.runtime.execute_resident,
                    model_name, device_batch)
            finally:
                if self.measure_phases:
                    with self._phase_lock:
                        self._exec_pending.pop(token, None)
            outputs, d2h_w = await loop.run_in_executor(
                self._d2h_pool, self.runtime.fetch_resident, out)
        except Exception as exc:  # noqa: BLE001 — device failure fails the batch
            log.exception("batch execution failed for %s", model_name)
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(exc)
            return
        if self.measure_phases:
            self._note_phase_windows(
                model_name, {"h2d": h2d_w, label: exec_w, "d2h": d2h_w},
                batch, token=token)
        self._batch_latency.observe(d2h_w[1] - t0, model=model_name)
        self._batch_size_hist.observe(n, model=model_name)
        self._h2d_bytes.inc(buf.nbytes, model=model_name)
        self._d2h_bytes.inc(_tree_nbytes(outputs), model=model_name)
        # Split-phase execution is single-runtime only (the multi-host
        # mirror loop keeps the fused path): no partial-degrade mode.
        await self._deliver(loop, model_name, servable, batch, outputs,
                            n, frozenset())

    async def _deliver(self, loop, model_name: str, servable,
                       batch: list[_Pending], outputs, n: int,
                       poisoned: frozenset) -> None:
        if poisoned:
            # Fail exactly the affected tasks — their rows ran on a zeros
            # shard (or a failed follower) and any "result" would be a
            # confidently wrong answer; the batch's other rows are good.
            # The typed RowPoisoned lets the worker redeliver exactly these
            # tasks through resilience instead of terminally failing them
            # (runtime/mesh/redelivery.py, docs/mesh_serving.md).
            from .mesh.redelivery import RowPoisoned
            log.error("batch for %s: %d of %d rows poisoned by a degraded "
                      "host; failing those tasks", model_name,
                      sum(1 for i in range(n) if i in poisoned), n)
            for i, p in enumerate(batch):
                if i in poisoned and not p.future.done():
                    p.future.set_exception(RowPoisoned())

        # Per-example postprocess runs on the executor, not the event loop:
        # a heavy postprocess (e.g. PNG-encoding 64 class maps) would
        # otherwise stall the flusher and every other request for the whole
        # fan-out. Each in-flight batch uses at most one executor task at a
        # time (device run XOR fan-out), so this never starves run_batch.
        # Snapshot the still-wanted indices first — don't postprocess
        # examples whose futures are already done (cancelled/timed out).
        wanted = [i for i, p in enumerate(batch) if not p.future.done()]

        def _fan_out() -> list:
            results: list = []
            for i in wanted:
                try:
                    results.append(
                        (True, servable.postprocess(_tree_index(outputs, i))))
                except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the exception is delivered to the example's future below, not dropped
                    results.append((False, exc))
            return results

        for i, (ok, value) in zip(
                wanted, await loop.run_in_executor(self._executor, _fan_out)):
            fut = batch[i].future
            if fut.done():  # cancelled while the fan-out ran
                continue
            if ok:
                fut.set_result(value)
            else:
                fut.set_exception(value)


def _tree_index(outputs, i: int):
    """Slice example ``i`` out of a pytree of batched arrays."""
    import jax
    return jax.tree_util.tree_map(lambda a: a[i], outputs)


def _tree_nbytes(outputs) -> int:
    """Total bytes across a pytree of fetched arrays."""
    import jax
    return sum(getattr(leaf, "nbytes", 0)
               for leaf in jax.tree_util.tree_leaves(outputs))
