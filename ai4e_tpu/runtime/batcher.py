"""Micro-batcher — packs queued requests into dense fixed-shape TPU batches.

THE architectural divergence from the reference (SURVEY.md §7 hard parts #1):
the reference dispatches one task per HTTP POST to a GPU container; a TPU mesh
wants large dense batches. The batcher sits between the request path and the
device:

- requests arrive one at a time (``submit`` returns a future);
- a flusher drains the pending queue whenever the device is free, taking up to
  ``max_bucket`` examples — under load the batch grows toward the biggest
  bucket (adaptive batching), idle requests leave at batch 1 with
  ``max_wait_ms`` bounding added latency;
- the batch is padded to the smallest compiled bucket (no recompiles, static
  shapes) and run on the mesh via a single executor thread (one TPU program
  at a time — the device is the serial resource);
- outputs fan back out to per-request futures; per-example postprocess errors
  fail only that request (failure isolation: one bad image fails one task,
  never the batch).

Backpressure: ``pending_count`` over ``max_pending`` → ``submit`` raises
``BatcherSaturated`` and the service returns 503, which the dispatcher already
treats as backpressure — the queue-depth-vs-device-utilisation translation of
the reference's per-replica thread cap (SURVEY.md §7 hard part #2).
"""

from __future__ import annotations

import asyncio
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from .registry import ModelRuntime

log = logging.getLogger("ai4e_tpu.batcher")


class BatcherSaturated(RuntimeError):
    pass


@dataclass
class _Pending:
    example: np.ndarray
    future: asyncio.Future
    enqueued: float = field(default_factory=time.perf_counter)
    priority: int = 0  # 0 = interactive, higher = background
    # Absolute wall-clock deadline (unix seconds; 0.0 = none): an entry
    # still pending when it passes is dropped at batch-cut time with
    # DeadlineExceeded instead of being padded onto the device
    # (admission/ — dead work never reaches the TPU).
    deadline_at: float = 0.0
    # Hop-ledger buffer (observability/ledger.HopLedger) the worker
    # passed with the request; the batcher stamps batch-cut and device
    # phases into it. None = no stamping (the default).
    ledger: object = None


class MicroBatcher:
    def __init__(
        self,
        runtime: ModelRuntime,
        max_wait_ms: float = 5.0,
        max_pending: int = 256,
        metrics: MetricsRegistry | None = None,
        pipeline_depth: int = 2,
        interactive_reserve: float = 0.25,
        priority_aging_s: float = 2.0,
        measure_phases: bool = False,
    ):
        self.runtime = runtime
        self.max_wait = max_wait_ms / 1000.0
        self.max_pending = max_pending
        # Priority isolation is enforced at BOTH gates:
        # - admission: background submits saturate at (1 - reserve) of the
        #   queue, so stacks can never eat the whole cap and 503 interactive
        #   traffic out of the batcher;
        # - batch cut: interactive-first, but a background item's effective
        #   priority decays by 1 class per ``priority_aging_s`` waited, so
        #   sustained interactive load delays stacks boundedly instead of
        #   starving them (0 disables aging → strict priority).
        self._background_cap = max(1, int(max_pending
                                          * (1.0 - interactive_reserve)))
        self.priority_aging_s = priority_aging_s
        self.metrics = metrics or DEFAULT_REGISTRY
        self._pending: dict[str, list[_Pending]] = {}
        self._wakeup: asyncio.Event = asyncio.Event()
        self._stop = False
        self._flusher: asyncio.Task | None = None
        # ``pipeline_depth`` device-feeding threads + an equal-slot window:
        # the device still serialises compute, but batch N+1's host work
        # (padding, dispatch, result transfer) overlaps batch N's device time
        # instead of waiting on its device_get. Depth 2 (double buffering) is
        # right for a locally-attached chip; a remote-attached TPU whose
        # host↔device link is long-fat (the axon tunnel: ~70 ms RTT) needs
        # more in-flight batches to fill the pipe — depth 6 measured 2.5×
        # the sustained tiles/s of depth 2 there.
        if pipeline_depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1, got {pipeline_depth}")
        self.pipeline_depth = pipeline_depth
        self._executor = ThreadPoolExecutor(max_workers=pipeline_depth,
                                            thread_name_prefix="tpu-batcher")
        self._window = asyncio.Semaphore(pipeline_depth)
        self._inflight_execs: set[asyncio.Task] = set()
        self._batch_size_hist = self.metrics.histogram(
            "ai4e_batch_size", "Executed batch sizes",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, float("inf")))
        self._batch_latency = self.metrics.histogram(
            "ai4e_batch_exec_seconds", "Device execution time per batch")
        self._queue_wait = self.metrics.histogram(
            "ai4e_batch_queue_wait_seconds", "Request wait before batching")
        self._pending_gauge = self.metrics.gauge(
            "ai4e_batcher_pending", "Requests waiting for a batch slot")
        self._inflight_gauge = self.metrics.gauge(
            "ai4e_batcher_inflight_batches",
            "Device batches currently in the pipeline window")
        # Link accounting (VERDICT r2 #3): actual bytes shipped host→device
        # per executed batch (bucket-padded input) and device→host (fetched
        # outputs) — the numbers that bound throughput on a remote-attached
        # TPU, reported per-request by the bench.
        self._h2d_bytes = self.metrics.counter(
            "ai4e_batch_h2d_bytes_total",
            "Host-to-device bytes shipped (padded batches)")
        self._d2h_bytes = self.metrics.counter(
            "ai4e_batch_d2h_bytes_total",
            "Device-to-host bytes fetched (batch outputs)")
        # Deadline drops at the batch cut (admission/): same series every
        # other hop reports into, labeled with THIS hop.
        self._expired_total = self.metrics.counter(
            "ai4e_admission_expired_total",
            "Requests dropped on deadline expiry, by hop/priority")
        # Device-phase decomposition (observability/, ROADMAP item 2's
        # overlap metric): off by default — the batch path and /metrics
        # content are byte-identical until AI4E_OBSERVABILITY_HOP_LEDGER
        # turns it on. When on, batches run through the runtime's
        # run_batch_phases (measured h2d / compile-or-execute / d2h),
        # each phase lands in its histogram, and the h2d seconds spent
        # while ANOTHER batch was executing accumulate into the overlap
        # counter — overlap ratio ≈ how well transfers hide under
        # compute (1.0 = fully hidden, the double-buffering goal).
        self.measure_phases = measure_phases
        if measure_phases:
            import threading
            self._phase_hist = self.metrics.histogram(
                "ai4e_device_phase_seconds",
                "Device-boundary phase durations (h2d/compile/execute/"
                "d2h) per batch")
            self._overlap_total = self.metrics.counter(
                "ai4e_batch_h2d_overlap_seconds_total",
                "H2D transfer seconds that overlapped another batch's "
                "execute phase")
            self._overlap_ratio = self.metrics.gauge(
                "ai4e_batch_overlap_ratio",
                "Cumulative h2d/execute overlap ratio (overlapped h2d "
                "seconds / total h2d seconds)")
            self._phase_lock = threading.Lock()
            # Completed execute windows (start, end) + in-flight batch
            # starts — the overlap denominator's counterparty. In-flight
            # windows are approximated from the batch's call start (the
            # exact execute start is known only at completion), which
            # slightly over-counts overlap; documented in
            # docs/observability.md.
            from collections import deque as _deque
            self._exec_windows = _deque(maxlen=64)
            self._exec_pending: dict[int, float] = {}
            self._h2d_seconds = 0.0
            self._h2d_overlap_seconds = 0.0

    # -- request side ------------------------------------------------------

    @property
    def pending_count(self) -> int:
        return sum(len(v) for v in self._pending.values())

    async def submit(self, model_name: str, example: np.ndarray,
                     priority: int = 0, deadline_at: float = 0.0,
                     ledger=None):
        """Queue one example; resolves to that example's postprocessed result.

        ``priority`` 0 is interactive (default); higher values are
        background classes (the batch API submits at 1). Every device batch
        is filled interactive-first, so a long background stack shares the
        device without queueing ahead of interactive latency — the
        isolation the reference gets only from separate container pools.

        ``deadline_at`` (absolute unix seconds; 0.0 = none): if the entry
        is still pending when the deadline passes, the await raises
        ``DeadlineExceeded`` at the next batch cut and the example never
        ships to the device (admission/).

        ``ledger`` (optional ``observability.ledger.HopLedger``): the
        batch cut and the device phases this example rides are stamped
        into it (``batched``/``h2d``/``execute``/``d2h``) — the worker
        flushes the buffer to the task store when the request finishes.
        """
        if self._stop:
            raise RuntimeError("batcher stopped")
        cap = self.max_pending if priority <= 0 else self._background_cap
        if self.pending_count >= cap:
            raise BatcherSaturated(
                f"batcher at {self.pending_count}/{cap} pending "
                f"(priority {priority})")
        servable = self.runtime.models[model_name]
        expected = tuple(servable.input_shape)
        if tuple(example.shape) != expected:
            raise ValueError(
                f"bad input shape {example.shape}, expected {expected}")
        fut = asyncio.get_running_loop().create_future()
        self._pending.setdefault(model_name, []).append(
            _Pending(example, fut, priority=priority,
                     deadline_at=deadline_at, ledger=ledger))
        self._pending_gauge.set(self.pending_count)
        self._wakeup.set()
        return await fut

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._stop = False
        self._flusher = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stop = True
        self._wakeup.set()
        if self._flusher is not None:
            await self._flusher
        if self._inflight_execs:
            await asyncio.gather(*self._inflight_execs,
                                 return_exceptions=True)
        self._executor.shutdown(wait=True)

    # -- flusher -----------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stop:
            if self.pending_count == 0:
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    continue
            # Brief accumulation window: let more requests join the batch.
            if self.max_wait > 0:
                first = min((p[0].enqueued for p in self._pending.values() if p),
                            default=time.perf_counter())
                window = self.max_wait - (time.perf_counter() - first)
                if window > 0 and self._max_queue_len() < self._largest_bucket():
                    await asyncio.sleep(window)
            for model_name in list(self._pending):
                if not self._pending.get(model_name):
                    continue
                # Acquire the window slot BEFORE carving the batch: while all
                # slots are busy, arriving requests keep joining the pending
                # queue, so the batch cut the moment a slot frees is as full
                # as possible (cutting first would freeze the batch at
                # whatever had arrived, then let it stale-wait).
                await self._window.acquire()
                batch = self._take_batch(model_name)
                if not batch:
                    self._window.release()
                    continue
                # Bounded pipelining: admit the batch and keep draining —
                # don't wait for its results.
                task = loop.create_task(
                    self._execute(loop, model_name, batch))
                self._inflight_execs.add(task)
                self._inflight_gauge.set(len(self._inflight_execs))

                def _done(t: asyncio.Task) -> None:
                    self._inflight_execs.discard(t)
                    self._inflight_gauge.set(len(self._inflight_execs))
                    self._window.release()

                task.add_done_callback(_done)

    def _max_queue_len(self) -> int:
        return max((len(v) for v in self._pending.values()), default=0)

    def _largest_bucket(self) -> int:
        return max((m.max_bucket for m in self.runtime.models.values()),
                   default=1)

    def _take_batch(self, model_name: str) -> list[_Pending]:
        queue = self._pending.get(model_name, [])
        if not queue:
            return []
        queue = self._sweep_expired(model_name, queue)
        if not queue:
            return []
        servable = self.runtime.models[model_name]
        take = min(len(queue), servable.max_bucket)
        if take < len(queue):
            # Cut interactive-first: a background stack never queues ahead
            # of fresh interactive requests when the batch can't hold
            # everyone — but waiting decays a class per priority_aging_s so
            # nothing starves. Within a class the aged key preserves
            # oldest-first. Full drains skip the sort.
            now = time.perf_counter()
            aging = self.priority_aging_s

            def effective(p: _Pending) -> float:
                if aging <= 0:
                    return float(p.priority)
                return p.priority - (now - p.enqueued) / aging

            queue = sorted(queue, key=effective)
        batch, rest = queue[:take], queue[take:]
        self._pending[model_name] = rest
        self._pending_gauge.set(self.pending_count)
        return batch

    def _sweep_expired(self, model_name: str,
                       queue: list[_Pending]) -> list[_Pending]:
        """Drop pending entries whose deadline passed while they queued —
        at the batch cut, the last gate before the device (admission/: zero
        expired examples ever reach ``_execute``). Their futures resolve to
        ``DeadlineExceeded`` so the worker can move the task to the
        terminal ``expired`` status. Deadline-free entries pass untouched;
        the all-deadline-free fast path allocates nothing."""
        now = time.time()
        if not any(p.deadline_at and p.deadline_at <= now for p in queue):
            return queue
        from ..admission.deadline import DeadlineExceeded, priority_name
        live: list[_Pending] = []
        for p in queue:
            if (p.deadline_at and p.deadline_at <= now
                    and not p.future.done()):
                p.future.set_exception(
                    DeadlineExceeded("batcher", p.deadline_at))
                self._expired_total.inc(hop="batcher",
                                        priority=priority_name(p.priority))
            else:
                live.append(p)
        self._pending[model_name] = live
        self._pending_gauge.set(self.pending_count)
        return live

    def _note_phases(self, model_name: str, t_call: float,
                     phases: dict, batch: list[_Pending]) -> None:
        """Account one phased batch: phase histograms, h2d/execute
        overlap, and per-request ledger stamps. ``t_call`` is the
        perf-counter start of the batch's device call."""
        for phase, dur in phases.items():
            self._phase_hist.observe(dur, phase=phase, model=model_name)
        h2d = phases.get("h2d", 0.0)
        exec_dur = phases.get("execute", phases.get("compile", 0.0))
        h2d_w = (t_call, t_call + h2d)
        exec_w = (h2d_w[1], h2d_w[1] + exec_dur)
        now = time.perf_counter()
        if h2d > 0:
            with self._phase_lock:
                overlap = 0.0
                for w0, w1 in self._exec_windows:
                    overlap += max(0.0, min(h2d_w[1], w1) - max(h2d_w[0], w0))
                for token, start in self._exec_pending.items():
                    if token != id(batch):
                        # In-flight batch: execute window approximated
                        # from its call start to now (over-counts by its
                        # own h2d time; see __init__ comment).
                        overlap += max(0.0, min(h2d_w[1], now)
                                       - max(h2d_w[0], start))
                overlap = min(overlap, h2d)
                self._exec_windows.append(exec_w)
                self._h2d_seconds += h2d
                self._h2d_overlap_seconds += overlap
                ratio = (self._h2d_overlap_seconds / self._h2d_seconds
                         if self._h2d_seconds > 0 else 0.0)
            self._overlap_total.inc(overlap, model=model_name)
            self._overlap_ratio.set(ratio)
        # Ledger stamps ride wall-clock time like every other hop:
        # convert the perf-counter anchors through "now".
        stamped = [p for p in batch if p.ledger is not None]
        if stamped:
            epoch_call = time.time() - (now - t_call)
            cursor = epoch_call
            for phase in ("h2d", "compile", "execute", "d2h"):
                dur = phases.get(phase)
                if dur is None:
                    continue
                for p in stamped:
                    p.ledger.stamp(phase, "device", t=cursor,
                                   ms=dur * 1e3)
                cursor += dur

    async def _execute(self, loop, model_name: str,
                       batch: list[_Pending]) -> None:
        servable = self.runtime.models[model_name]
        n = len(batch)
        bucket = servable.bucket_for(n)
        now = time.perf_counter()
        for p in batch:
            self._queue_wait.observe(now - p.enqueued, model=model_name)

        padded = np.zeros((bucket, *servable.input_shape),
                          servable.input_dtype)
        for i, p in enumerate(batch):
            padded[i] = p.example
            if p.ledger is not None:
                p.ledger.stamp("batched", "batcher",
                               reason=f"size {n} bucket {bucket}")

        t0 = time.perf_counter()
        # Phase-decomposed path (observability): measured h2d / execute /
        # d2h plus transfer/execute overlap accounting. Falls back to
        # run_batch_report — which surfaces rows a degraded follower
        # invalidated (multihost zeros-shard path) — and plain run_batch
        # for duck-typed runtimes without either.
        phased = (self.measure_phases
                  and getattr(self.runtime, "run_batch_phases", None)
                  is not None)
        runner = getattr(self.runtime, "run_batch_report", None)
        phases: dict = {}
        if phased:
            with self._phase_lock:
                self._exec_pending[id(batch)] = t0
        try:
            if phased:
                outputs, poisoned, phases = await loop.run_in_executor(
                    self._executor, self.runtime.run_batch_phases,
                    model_name, padded)
            elif runner is not None:
                outputs, poisoned = await loop.run_in_executor(
                    self._executor, runner, model_name, padded)
            else:
                outputs = await loop.run_in_executor(
                    self._executor, self.runtime.run_batch, model_name, padded)
                poisoned = frozenset()
        except Exception as exc:  # noqa: BLE001 — device failure fails the batch
            log.exception("batch execution failed for %s", model_name)
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(exc)
            return
        finally:
            if phased:
                with self._phase_lock:
                    self._exec_pending.pop(id(batch), None)
        if phases:
            self._note_phases(model_name, t0, phases, batch)
        self._batch_latency.observe(time.perf_counter() - t0, model=model_name)
        self._batch_size_hist.observe(n, model=model_name)
        self._h2d_bytes.inc(padded.nbytes, model=model_name)
        self._d2h_bytes.inc(_tree_nbytes(outputs), model=model_name)
        if poisoned:
            # Fail exactly the affected tasks — their rows ran on a zeros
            # shard (or a failed follower) and any "result" would be a
            # confidently wrong answer; the batch's other rows are good.
            log.error("batch for %s: %d of %d rows poisoned by a degraded "
                      "host; failing those tasks", model_name,
                      sum(1 for i in range(n) if i in poisoned), n)
            for i, p in enumerate(batch):
                if i in poisoned and not p.future.done():
                    p.future.set_exception(RuntimeError(
                        "result invalidated: a worker host degraded while "
                        "executing this row's shard"))

        # Per-example postprocess runs on the executor, not the event loop:
        # a heavy postprocess (e.g. PNG-encoding 64 class maps) would
        # otherwise stall the flusher and every other request for the whole
        # fan-out. Each in-flight batch uses at most one executor task at a
        # time (device run XOR fan-out), so this never starves run_batch.
        # Snapshot the still-wanted indices first — don't postprocess
        # examples whose futures are already done (cancelled/timed out).
        wanted = [i for i, p in enumerate(batch) if not p.future.done()]

        def _fan_out() -> list:
            results: list = []
            for i in wanted:
                try:
                    results.append(
                        (True, servable.postprocess(_tree_index(outputs, i))))
                except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the exception is delivered to the example's future below, not dropped
                    results.append((False, exc))
            return results

        for i, (ok, value) in zip(
                wanted, await loop.run_in_executor(self._executor, _fan_out)):
            fut = batch[i].future
            if fut.done():  # cancelled while the fan-out ran
                continue
            if ok:
                fut.set_result(value)
            else:
                fut.set_exception(value)


def _tree_index(outputs, i: int):
    """Slice example ``i`` out of a pytree of batched arrays."""
    import jax
    return jax.tree_util.tree_map(lambda a: a[i], outputs)


def _tree_nbytes(outputs) -> int:
    """Total bytes across a pytree of fetched arrays."""
    import jax
    return sum(getattr(leaf, "nbytes", 0)
               for leaf in jax.tree_util.tree_leaves(outputs))
