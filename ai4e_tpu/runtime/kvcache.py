"""Pooled KV-cache decode runtime — the device side of continuous
batching (``runtime/decode.py`` owns the scheduling).

The cache is ONE preallocated slot-pool buffer per tensor::

    k, v : (layers, slots, heads, max_len, head_dim)

keyed by ``(model, params_version)`` — a hot weight reload bumps the
version and the engine invalidates (``reset_cache``) then re-prefills,
the same key contract as rescache (a KV block computed under old weights
is a stale cached result). Slots are rows of that buffer; admission and
release are pure bookkeeping in ``decode.SlotPool`` — the device never
reallocates per request.

Three compiled programs serve the whole path, none of which may compile
on the serving path (``warm()`` executes every one — the AOT-warm
discipline ``ModelRuntime.warmup`` applies to batch buckets):

- **prefill** — full causal attention over ONE padded prompt, per
  prompt bucket (``ladder.DECODE_PROMPT_BUCKETS``: prompts pad to the
  smallest fitting bucket, so XLA compiles ``len(buckets)`` prefill
  programs, not one per prompt length);
- **insert** — ``dynamic_update_slice`` of a prefill's KV block into a
  slot row (slot index is a traced scalar: one program per bucket, any
  slot);
- **step** — one decode step over the WHOLE pool: every slot advances
  one token (inactive slots ride along masked; their rows are garbage a
  later prefill overwrites). One fixed shape → exactly one program.

Buffer donation: the step and insert programs consume the cache and
return the updated one; on non-CPU backends the input buffer is donated
so the pool exists on-device exactly once.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any

import numpy as np

log = logging.getLogger("ai4e_tpu.kvcache")


@dataclass
class LMServable:
    """A deployable autoregressive LM — the decode path's analogue of
    ``registry.ServableModel`` (which stays the batch path's contract:
    LMs never enter ``runtime.models``, the MicroBatcher cannot serve
    them)."""

    name: str
    model: Any                   # models.seqformer.SeqFormerLM
    params: Any
    vocab_size: int
    max_len: int
    eos_id: int | None = None
    version: str = "1.0"
    checkpoint_path: str | None = None
    params_version: int = 1
    # Rollout generation (rollout/, docs/deployment.md) — same contract
    # as registry.ServableModel.generation: the cross-replica deploy
    # coordinate the canary split routes on; the reload verb sets it.
    generation: int = 1


def build_lm_servable(name: str = "lm", vocab_size: int = 512,
                      max_len: int = 256, dim: int = 64, depth: int = 2,
                      heads: int = 4, eos_id: int | None = None,
                      rng=None, **_) -> LMServable:
    """Build a SeqFormerLM servable for the streaming path (the ``**_``
    sink mirrors the batch families: spec-driven callers may pass keys
    this family ignores)."""
    from ..models.seqformer import create_seqformer_lm
    model, params = create_seqformer_lm(
        rng=rng, vocab_size=vocab_size, max_len=max_len, dim=dim,
        depth=depth, heads=heads)
    return LMServable(name=name, model=model, params=params,
                      vocab_size=vocab_size, max_len=max_len, eos_id=eos_id)


class PagedDecodeRuntime:
    """The ``DecodeEngine`` backend over a real JAX model. All methods
    are blocking — the engine runs them on its single device-executor
    thread (the device is the serial resource, batcher discipline)."""

    def __init__(self, servable: LMServable, slots: int = 8,
                 prompt_buckets=None, donate: bool | None = None):
        from .ladder import DECODE_PROMPT_BUCKETS
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.servable = servable
        self.name = servable.name
        self.slots = slots
        self.max_len = servable.max_len
        self.eos_id = servable.eos_id
        raw = tuple(prompt_buckets) if prompt_buckets else (
            DECODE_PROMPT_BUCKETS)
        # Clamp to the cache length and force coverage: the top bucket is
        # always max_len, so every admissible prompt (< max_len) has a
        # compiled program — no serving-path compile, ever.
        self.prompt_buckets = tuple(sorted(
            {min(int(b), self.max_len) for b in raw} | {self.max_len}))
        self._k = None
        self._v = None
        self._donate = donate
        self._programs = None

    # -- cache lifecycle ---------------------------------------------------

    @property
    def params_version(self) -> int:
        return self.servable.params_version

    def cache_nbytes(self) -> int:
        """Resident bytes of the pooled cache (both tensors) — the
        number the memory math in docs/streaming.md bounds."""
        m = self.servable.model
        head_dim = m.dim // m.heads
        return (2 * m.depth * self.slots * m.heads * self.max_len
                * head_dim * np.dtype(np.float32).itemsize)

    def reset_cache(self) -> None:
        """Drop + reallocate the pooled cache (hot-reload invalidation:
        blocks computed under the old weights must never serve)."""
        import jax.numpy as jnp
        m = self.servable.model
        head_dim = m.dim // m.heads
        shape = (m.depth, self.slots, m.heads, self.max_len, head_dim)
        self._k = jnp.zeros(shape, jnp.float32)
        self._v = jnp.zeros(shape, jnp.float32)

    def _ensure(self) -> None:
        if self._k is None:
            self.reset_cache()
        if self._programs is None:
            self._build_programs()

    def _build_programs(self) -> None:
        import jax
        from ..models.seqformer import SeqFormerLM
        model = self.servable.model
        if self._donate is None:
            # CPU XLA cannot donate (every run would warn); on device
            # backends donation keeps the pool resident exactly once.
            self._donate = jax.default_backend() != "cpu"
        donate_step = (2, 3) if self._donate else ()
        donate_insert = (0, 1) if self._donate else ()

        def prefill(params, tokens, length):
            return model.apply(params, tokens, length,
                               method=SeqFormerLM.prefill)

        def step(params, tokens, k, v, position):
            return model.apply(params, tokens, k, v, position,
                               method=SeqFormerLM.decode_step)

        def insert(k, v, k_block, v_block, slot):
            zero = (0, slot, 0, 0, 0)
            # Blocks arrive as (depth, 1, H, P, hd) — rank-matched to the
            # pool, so one dynamic_update_slice lands the whole prompt.
            return (jax.lax.dynamic_update_slice(k, k_block, zero),
                    jax.lax.dynamic_update_slice(v, v_block, zero))

        self._programs = {
            "prefill": jax.jit(prefill),
            "step": jax.jit(step, donate_argnums=donate_step),
            "insert": jax.jit(insert, donate_argnums=donate_insert),
        }

    # -- engine backend surface -------------------------------------------

    def bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def prefill_into(self, slot: int, tokens) -> int:
        """Run the prompt through the prefill program (padded to its
        bucket), write its KV block into ``slot``, return the first
        generated token id."""
        self._ensure()
        n = len(tokens)
        if not 0 < n < self.max_len:
            raise ValueError(
                f"prompt of {n} tokens must be in [1, {self.max_len})")
        bucket = self.bucket_for(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        token, k_block, v_block = self._programs["prefill"](
            self.servable.params, padded, np.asarray([n], np.int32))
        self._k, self._v = self._programs["insert"](
            self._k, self._v, k_block, v_block, np.int32(slot))
        return int(token[0])

    def step(self, tokens, positions, active) -> list[int]:
        """One decode step over the pool. ``active`` is advisory — the
        program computes every slot; inactive rows are garbage the
        engine never reads."""
        self._ensure()
        del active
        out, self._k, self._v = self._programs["step"](
            self.servable.params, np.asarray(tokens, np.int32),
            self._k, self._v, np.asarray(positions, np.int32))
        return [int(t) for t in np.asarray(out)]

    # -- weights -----------------------------------------------------------

    def reload_params(self, new_params) -> int:
        """Hot-swap the LM's weights (same tree contract as
        ``ModelRuntime.reload_params``); bumps ``params_version`` so the
        engine invalidates the pooled cache at its next tick."""
        import jax
        import jax.numpy as jnp

        def spec_of(tree):
            return jax.tree.map(
                lambda a: (tuple(a.shape), jnp.result_type(a).name), tree)

        if spec_of(self.servable.params) != spec_of(new_params):
            raise ValueError(
                "checkpoint tree does not match the served model")
        self.servable.params = new_params
        self.servable.params_version += 1
        return self.servable.params_version

    # -- warmup ------------------------------------------------------------

    def warm(self) -> float:
        """Execute every program once — ``len(prompt_buckets)`` prefill +
        insert pairs and the one step program — so nothing compiles on
        the serving path, then reset the cache to a clean pool. Returns
        wall seconds (exported by the worker boot like batch warmup)."""
        self._ensure()
        t0 = time.perf_counter()
        for bucket in self.prompt_buckets:
            n = min(bucket, self.max_len - 1)
            self.prefill_into(0, [1] * n)
        self.step([0] * self.slots, [1] * self.slots, [True] * self.slots)
        self.reset_cache()
        seconds = time.perf_counter() - t0
        log.info("decode warmup %s: %d prompt buckets + step in %.1fs",
                 self.name, len(self.prompt_buckets), seconds)
        return seconds
