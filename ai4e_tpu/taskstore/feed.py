"""Per-shard terminal-event change feed — the long-poll fan-out surface.

Before sharding, every gateway long-poll waiter rode a listener attached
straight to the one store and re-read the record from the store on every
wakeup. With N shards and ~100k concurrent watchers that shape becomes N
× watchers listener registrations and a store read per wake. This module
inverts it: each shard publishes its terminal transitions into ONE
``ShardChangeFeed``; watchers park a future on the feed keyed by TaskId
and are woken WITH the terminal record itself — no store re-poll on the
wake path, and the whole watcher population rides exactly N feed
attachments (one relay per shard, ``sharding.ShardedTaskStore._relay``).

The no-missed-wakeup contract (docs/concurrency.md, regression in
``tests/test_race_regressions.py``): a watcher that read a non-terminal
status and then attaches races the terminal event. The feed closes the
window structurally — ``publish`` records the event in a bounded
recent-terminal replay map and collects waiters under the SAME lock that
``wait_terminal`` checks that map and registers under, so an event is
either seen at attach time (replay) or delivered to the registered
future; there is no interleaving where it is neither.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from collections import OrderedDict
from dataclasses import replace

from .task import APITask, TaskStatus

log = logging.getLogger("ai4e_tpu.taskstore.feed")


class ShardChangeFeed:
    """Terminal-transition fan-out for one shard of the task keyspace.

    ``publish`` may fire from any thread (store listeners run outside the
    store lock on whatever thread mutated); waiters may live on any event
    loop — wakes cross loops via ``call_soon_threadsafe`` and take the
    same-loop fast path when the publisher is already on the waiter's
    loop (the single-process assembly's common case).
    """

    def __init__(self, shard_index: int = 0, recent: int = 4096):
        self.shard_index = shard_index
        # Monotonic event counter — observability (the /shards endpoint
        # reports it as the feed's position).
        self.seq = 0
        self._recent_cap = recent
        # task_id -> terminal record: the bounded replay window that closes
        # the attach-vs-event race. Insertion-ordered; oldest evicted first.
        self._recent: OrderedDict[str, APITask] = OrderedDict()
        # task_id -> frozenset[(loop, future)] — copy-on-write like the
        # gateway's waiter map, for the same reason: publish iterates from
        # any thread while waiters attach/detach on their loops.
        self._waiters: dict[str, frozenset] = {}
        self._lock = threading.Lock()

    # -- publish side (the shard relay) ------------------------------------

    def publish(self, task: APITask) -> None:
        """Feed one store transition. Non-terminal transitions wake nobody,
        but they DO invalidate the task's replay entry: a terminal task
        re-entering the lifecycle (redrive, reaper requeue, client
        re-submission under the same TaskId) must not let the NEXT
        long-poll answer instantly with the previous run's record."""
        if task.canonical_status not in TaskStatus.TERMINAL:
            with self._lock:
                self._recent.pop(task.task_id, None)
            return
        if task.body:
            # Watchers only ever need the wire shape (to_dict carries no
            # body): holding request payloads in the replay map would pin
            # up to ``recent`` bodies per shard past store retention —
            # exactly the memory the retention sweep exists to bound.
            task = replace(task, body=b"")
        with self._lock:
            self.seq += 1
            self._recent[task.task_id] = task
            self._recent.move_to_end(task.task_id)
            while len(self._recent) > self._recent_cap:
                self._recent.popitem(last=False)
            waiters = self._waiters.pop(task.task_id, frozenset())
        for loop, fut in waiters:
            self._wake(loop, fut, task)

    def invalidate(self, task_ids) -> None:
        """Drop replay entries for a set of tasks — the rebalance handoff
        calls this on the SOURCE shard's feed: the moved range's future
        transitions publish to the destination's feed, so a stale terminal
        record here would outlive any later redrive of the task (and
        answer a long-poll with the previous run's result if the slot
        ever moves back)."""
        with self._lock:
            for task_id in task_ids:
                self._recent.pop(task_id, None)

    @staticmethod
    def _wake(loop, fut, record) -> None:
        def setter() -> None:
            if not fut.done():
                fut.set_result(record)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is running:
            setter()
        else:
            try:
                loop.call_soon_threadsafe(setter)
            except RuntimeError:  # waiter's loop already closed — it's gone
                log.debug("feed wake for %s dropped: waiter loop closed",
                          record.task_id)

    # -- watcher side (gateway long-poll) ----------------------------------

    async def wait_terminal(self, task_id: str,
                            timeout: float) -> APITask | None:
        """Park until ``task_id`` reaches a terminal status; returns the
        terminal record, or None when ``timeout`` expires first. The
        replay-map check and the waiter registration happen under the
        feed lock, so a terminal event concurrent with attach is either
        returned immediately or delivered to the future — never missed."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        entry = (loop, fut)
        with self._lock:
            found = self._recent.get(task_id)
            if found is None:
                self._waiters[task_id] = self._waiters.get(
                    task_id, frozenset()) | {entry}
        if found is not None:
            return found
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            self._drop_waiter(task_id, entry)

    def _drop_waiter(self, task_id: str, entry) -> None:
        with self._lock:
            entries = self._waiters.get(task_id)
            if not entries:
                return
            remaining = frozenset(e for e in entries if e is not entry)
            if remaining:
                self._waiters[task_id] = remaining
            else:
                del self._waiters[task_id]

    # -- introspection ------------------------------------------------------

    def recent_terminal(self, task_id: str) -> APITask | None:
        """The task's terminal record if it terminated within the replay
        window — the attach-race check, also usable as a read-free probe."""
        with self._lock:
            return self._recent.get(task_id)

    @property
    def watcher_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._waiters.values())
