"""Pluggable result backends — the object-storage slot for large outputs.

The reference grants its model containers blob-storage access so batch jobs
can write big outputs outside the task record
(``APIs/helpers/assign_storage_auth_to_aks.sh:9-17`` assigns Storage Blob Data
Contributor to the AKS identity). Here the same slot is a small interface the
task store routes large results through instead of holding them in memory:

- ``FileResultBackend`` — filesystem-rooted implementation. Locally that's a
  directory; in a GKE deployment the root is a mounted GCS FUSE volume or PD,
  which is exactly how the charts mount the checkpoint store
  (``deploy/charts/checkpoints-pvc.yaml``). A native GCS client would be a
  third implementation of the same two methods; the store doesn't care.

Keys are ``{task_id}`` or ``{task_id}:{stage}``; the backend maps them to
filesystem-safe names itself.
"""

from __future__ import annotations

import os


class ResultBackend:
    """Interface: durable blob storage for task results."""

    def put(self, key: str, data: bytes, content_type: str) -> None:
        raise NotImplementedError

    def get(self, key: str) -> tuple[bytes, str] | None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def open(self, key: str):
        """Streaming read: ``(file_like, content_type, size)`` or None.
        Default adapts ``get`` (in-memory); file backends override with a
        real handle so multi-MB results never buffer whole."""
        found = self.get(key)
        if found is None:
            return None
        import io
        data, content_type = found
        return io.BytesIO(data), content_type, len(data)


class FileResultBackend(ResultBackend):
    """Results as files under a root directory (local dir, PD mount, or GCS
    FUSE mount). Each result is two files: ``{name}.bin`` (payload) and
    ``{name}.meta`` (content type), written tmp+rename so a crashed write
    never leaves a half-result readable."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _name(self, key: str) -> str:
        # Injective escaping: task ids are GUID hex but the stage suffix is
        # free-form ("/", ":", ...); a lossy substitution would let two
        # stages collide on one file and silently overwrite each other.
        from urllib.parse import quote
        return quote(key, safe="")

    def put(self, key: str, data: bytes, content_type: str) -> None:
        name = self._name(key)
        for suffix, payload in ((".bin", data),
                                (".meta", content_type.encode())):
            tmp = os.path.join(self.root, name + suffix + ".tmp")
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.root, name + suffix))

    def get(self, key: str) -> tuple[bytes, str] | None:
        name = self._name(key)
        try:
            with open(os.path.join(self.root, name + ".bin"), "rb") as f:
                data = f.read()
            with open(os.path.join(self.root, name + ".meta"), "rb") as f:
                content_type = f.read().decode()
        except FileNotFoundError:
            return None
        return data, content_type

    def delete(self, key: str) -> None:
        name = self._name(key)
        for suffix in (".bin", ".meta"):
            try:
                os.unlink(os.path.join(self.root, name + suffix))
            except FileNotFoundError:
                pass

    def open(self, key: str):
        name = self._name(key)
        try:
            with open(os.path.join(self.root, name + ".meta"), "rb") as f:
                content_type = f.read().decode()
            fh = open(os.path.join(self.root, name + ".bin"), "rb")  # noqa: SIM115
        except FileNotFoundError:
            return None
        size = os.fstat(fh.fileno()).st_size
        return fh, content_type, size
