from .results import FileResultBackend, ResultBackend
from .store import InMemoryTaskStore, JournaledTaskStore, TaskNotFound
from .task import APITask, TaskStatus, endpoint_path, new_task_id

__all__ = [
    "APITask",
    "TaskStatus",
    "endpoint_path",
    "new_task_id",
    "InMemoryTaskStore",
    "JournaledTaskStore",
    "TaskNotFound",
    "FileResultBackend",
    "ResultBackend",
]
