from .journal import JournalCorruptError
from .results import FileResultBackend, ResultBackend
from .store import (
    FollowerTaskStore,
    InMemoryTaskStore,
    JournalDegradedError,
    JournaledTaskStore,
    NotOwnerError,
    NotPrimaryError,
    StaleEpochError,
    StoreClosedError,
    TaskNotFound,
)
from .task import APITask, TaskStatus, endpoint_path, new_task_id

__all__ = [
    "APITask",
    "TaskStatus",
    "endpoint_path",
    "new_task_id",
    "InMemoryTaskStore",
    "JournaledTaskStore",
    "FollowerTaskStore",
    "JournalCorruptError",
    "JournalDegradedError",
    "NotOwnerError",
    "NotPrimaryError",
    "StaleEpochError",
    "StoreClosedError",
    "TaskNotFound",
    "FileResultBackend",
    "ResultBackend",
]
