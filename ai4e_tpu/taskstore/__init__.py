from .results import FileResultBackend, ResultBackend
from .store import (
    FollowerTaskStore,
    InMemoryTaskStore,
    JournaledTaskStore,
    NotPrimaryError,
    StaleEpochError,
    TaskNotFound,
)
from .task import APITask, TaskStatus, endpoint_path, new_task_id

__all__ = [
    "APITask",
    "TaskStatus",
    "endpoint_path",
    "new_task_id",
    "InMemoryTaskStore",
    "JournaledTaskStore",
    "FollowerTaskStore",
    "NotPrimaryError",
    "StaleEpochError",
    "TaskNotFound",
    "FileResultBackend",
    "ResultBackend",
]
