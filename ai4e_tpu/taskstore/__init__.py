from .task import APITask, TaskStatus, endpoint_path, new_task_id
from .store import InMemoryTaskStore, JournaledTaskStore, TaskNotFound

__all__ = [
    "APITask",
    "TaskStatus",
    "endpoint_path",
    "new_task_id",
    "InMemoryTaskStore",
    "JournaledTaskStore",
    "TaskNotFound",
]
