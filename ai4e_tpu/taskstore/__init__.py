from .results import FileResultBackend, ResultBackend
from .store import (
    FollowerTaskStore,
    InMemoryTaskStore,
    JournaledTaskStore,
    NotOwnerError,
    NotPrimaryError,
    StaleEpochError,
    StoreClosedError,
    TaskNotFound,
)
from .task import APITask, TaskStatus, endpoint_path, new_task_id

__all__ = [
    "APITask",
    "TaskStatus",
    "endpoint_path",
    "new_task_id",
    "InMemoryTaskStore",
    "JournaledTaskStore",
    "FollowerTaskStore",
    "NotOwnerError",
    "NotPrimaryError",
    "StaleEpochError",
    "StoreClosedError",
    "TaskNotFound",
    "FileResultBackend",
    "ResultBackend",
]
