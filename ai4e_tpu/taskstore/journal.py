"""Journal record envelope — checksummed, hash-chained, salvageable.

The platform's whole HA story rests on one claim: *journal file = durable
truth* (``docs/sharding.md``'s ``kill_shard_primary`` contract, the role
the reference bought from managed Redis persistence,
``RedisConnection.cs:12-38``). This module makes that claim verifiable
below the process boundary:

- **Record envelope.** Every journal line the store writes is wrapped as

      J1:<crc32c>:<chain>:<payload JSON>

  where ``crc32c`` is the CRC-32C (Castagnoli) of the payload bytes and
  ``chain`` is a digest chained from the PREVIOUS record's checksum
  (``chain_n = crc32c(chain_{n-1} || crc_n)``, genesis ``00000000``).
  The checksum detects bit-rot and short writes at the exact record; the
  chain detects a forked or spliced history, and two stores that hold
  the same journal bytes hold the same **chain head** — primary/replica
  divergence is a string comparison (``GET /v1/taskstore/shards``).

- **Legacy lines.** A line that does not start with ``J1:`` is a
  checksum-less record from a pre-envelope journal. It replays and
  absorbs verbatim (migration is a restart, not a rewrite); the chain
  still advances over it (checksum of the raw line), so a mixed journal
  has a well-defined head — it just cannot *verify* those records.

- **Salvage vs quarantine.** A failure in the FINAL line of the file is
  a torn tail (the canonical mid-write crash shape): ``salvage``
  truncates to the end of the last verified record — before the
  append handle ever opens, so the next append can never concatenate
  onto torn bytes — and writes a sidecar report. A failure with more
  records AFTER it is interior corruption: replaying past it would
  silently fork history, so the store refuses loudly with the byte
  offset (``JournalCorruptError``; operator path in
  docs/durability.md).

``python -m ai4e_tpu.taskstore.journal <path>`` verifies a journal
offline and prints per-record verdicts plus the chain head.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

# Chain value before any record — also the chain head of an empty journal.
GENESIS = "00000000"

ENVELOPE_PREFIX = "J1:"
# "J1:" + 8 hex crc + ":" + 8 hex chain + ":" → payload starts at 21.
_PAYLOAD_AT = 21

_HEX = frozenset("0123456789abcdef")


def _crc32c_table() -> list[int]:
    poly = 0x82F63B78  # CRC-32C (Castagnoli), reflected
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_TABLE = _crc32c_table()


def crc32c(data: bytes) -> int:
    """Software CRC-32C (Castagnoli — the checksum iSCSI/ext4 use for
    exactly this torn-write-detection job). Pure-stdlib by design: the
    container pins its dependency set, and journal records are
    control-plane sized (a table-driven byte loop is microseconds per
    record, amortized to nothing against the JSON serialization beside
    it).

    The control-plane-sized premise does NOT hold for inline result
    records: without a result backend (or below the offload threshold)
    a result body journals in full, and a multi-MB payload pays ~0.3 s
    per MB here — under the store lock, and again per retained record
    at every compaction/replay. That path already pays the same order
    in hex+JSON encoding beside it, so the remedy is configuring the
    result backend (``result_offload_threshold``), not a faster
    checksum (``zlib.crc32`` would be ~300x quicker but isn't the
    Castagnoli polynomial the format commits to)."""
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def chain_next(prev_chain: str, crc_hex: str) -> str:
    """Advance the chain over one record: digest of the previous chain
    value concatenated with this record's checksum. Any dropped,
    reordered, or substituted record changes every chain value after it."""
    return f"{crc32c((prev_chain + crc_hex).encode('ascii')):08x}"


class JournalCorruptError(RuntimeError):
    """A journal record failed checksum/chain verification somewhere a
    silent skip would fork history — an interior record on open, or a
    replicated line mid-stream. Carries the byte ``offset`` (own-file
    scans) or ``line_no`` so the operator can find the record
    (docs/durability.md#corrupt-journal-runbook)."""

    def __init__(self, message: str, offset: int | None = None,
                 line_no: int | None = None, reason: str = "checksum"):
        super().__init__(message)
        self.offset = offset
        self.line_no = line_no
        self.reason = reason


def encode_record(rec: dict, prev_chain: str) -> tuple[str, str]:
    """Serialize one record into its enveloped line (no trailing newline);
    returns ``(line, new_chain)``."""
    payload = json.dumps(rec)
    crc_hex = f"{crc32c(payload.encode('utf-8')):08x}"
    chain = chain_next(prev_chain, crc_hex)
    return f"{ENVELOPE_PREFIX}{crc_hex}:{chain}:{payload}", chain


def verify_line(line: str, prev_chain: str | None
                ) -> tuple[dict, str | None, bool]:
    """Verify + decode ONE journal line (stripped, no newline).

    Returns ``(payload_record, new_chain, legacy)``. ``prev_chain=None``
    means chain continuity is unknown (a follower that attached
    mid-stream): the checksum is still verified and the line's own chain
    value is adopted. Raises ``JournalCorruptError`` on any mismatch or
    unparseable payload — the caller decides whether the failure is a
    salvageable tail or a quarantined interior record."""
    if not line.startswith(ENVELOPE_PREFIX):
        # Legacy checksum-less record (pre-envelope journal): accepted for
        # migration; the chain advances over the raw bytes so the head
        # stays comparable across stores holding the same file.
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            raise JournalCorruptError(
                f"unparseable legacy journal line: {exc}",
                reason="legacy-json") from exc
        if not isinstance(rec, dict):
            raise JournalCorruptError(
                "legacy journal line is not a JSON object",
                reason="legacy-json")
        crc_hex = f"{crc32c(line.encode('utf-8')):08x}"
        # With an unknown predecessor a legacy line cannot anchor the
        # chain (it carries no chain value of its own) — stay unanchored.
        chain = (chain_next(prev_chain, crc_hex)
                 if prev_chain is not None else None)
        return rec, chain, True
    crc_hex = line[3:11]
    chain_hex = line[12:20]
    if (len(line) < _PAYLOAD_AT or line[11] != ":" or line[20] != ":"
            or not _HEX.issuperset(crc_hex)
            or not _HEX.issuperset(chain_hex)):
        raise JournalCorruptError("malformed journal envelope",
                                  reason="envelope")
    payload = line[_PAYLOAD_AT:]
    actual = f"{crc32c(payload.encode('utf-8')):08x}"
    if actual != crc_hex:
        raise JournalCorruptError(
            f"journal record checksum mismatch (stored {crc_hex}, "
            f"computed {actual})", reason="checksum")
    if prev_chain is not None:
        expect = chain_next(prev_chain, crc_hex)
        if expect != chain_hex:
            raise JournalCorruptError(
                f"journal chain broken (stored {chain_hex}, expected "
                f"{expect}) — a record before this one was dropped or "
                "substituted", reason="chain")
    try:
        rec = json.loads(payload)
    except json.JSONDecodeError as exc:
        raise JournalCorruptError(
            f"journal payload checksums clean but fails JSON parse: {exc}",
            reason="json") from exc
    return rec, chain_hex, False


@dataclass
class ScanResult:
    """One verification pass over a journal file."""
    records: int = 0
    legacy_records: int = 0
    good_bytes: int = 0          # end offset of the last verified record
    chain_head: str = GENESIS
    # Set when verification failed: byte offset + 1-based line number of
    # the failing record, why, and whether anything follows it.
    bad_offset: int | None = None
    bad_line_no: int | None = None
    bad_reason: str | None = None
    tail_bytes: int = 0          # bytes from bad_offset to EOF
    interior: bool = False       # a later line exists → NOT salvageable
    decoded: list[dict] = field(default_factory=list, repr=False)

    @property
    def clean(self) -> bool:
        return self.bad_offset is None


def scan_journal(path: str, keep_records: bool = False) -> ScanResult:
    """Verify every record + the chain, without applying anything.

    Stops at the first failure and classifies it: a failing FINAL line
    (including an unterminated trailing fragment) is a torn tail — the
    mid-write crash shape ``salvage`` truncates; a failing line with any
    non-empty line after it is interior corruption (``interior=True``)."""
    out = ScanResult()
    with open(path, "rb") as fh:
        data = fh.read()
    offset = 0
    line_no = 0
    n = len(data)
    while offset < n:
        nl = data.find(b"\n", offset)
        end = n if nl == -1 else nl + 1
        raw = data[offset:end]
        line_no += 1
        stripped = raw.strip()
        if not stripped:
            out.good_bytes = end
            offset = end
            continue
        failure: JournalCorruptError | None = None
        if nl == -1:
            # Unterminated trailing fragment: torn by definition — even a
            # fragment that happens to parse must not be trusted (the
            # crash interrupted its write; more bytes were coming).
            failure = JournalCorruptError(
                "unterminated final journal line", reason="torn")
        else:
            try:
                rec, chain, legacy = verify_line(
                    stripped.decode("utf-8", errors="strict"),
                    out.chain_head)
            except (JournalCorruptError, UnicodeDecodeError) as exc:
                failure = (exc if isinstance(exc, JournalCorruptError)
                           else JournalCorruptError(
                               f"undecodable journal bytes: {exc}",
                               reason="encoding"))
        if failure is not None:
            out.bad_offset = offset
            out.bad_line_no = line_no
            out.bad_reason = failure.reason
            out.tail_bytes = n - offset
            # Anything non-empty AFTER the failing line means replay
            # would have to skip a record mid-history — quarantine.
            out.interior = bool(data[end:].strip())
            return out
        out.records += 1
        out.legacy_records += int(legacy)
        out.chain_head = chain
        out.good_bytes = end
        if keep_records:
            out.decoded.append(rec)
        offset = end
    return out


@dataclass
class SalvageReport:
    path: str
    truncated_at: int
    dropped_bytes: int
    reason: str
    records_kept: int
    chain_head: str

    def to_dict(self) -> dict:
        return {"path": self.path, "truncated_at": self.truncated_at,
                "dropped_bytes": self.dropped_bytes, "reason": self.reason,
                "records_kept": self.records_kept,
                "chain_head": self.chain_head}


def salvage(path: str, scan: ScanResult | None = None
            ) -> SalvageReport | None:
    """Repair a torn tail in place — BEFORE any append handle opens.

    Returns None when the journal is clean. On a torn final record:
    truncates the file to the end of the last verified record (an
    ``"a"``-mode handle opened afterwards can never concatenate onto torn
    bytes — the exact bug a skip-only replay fix leaves behind), writes a
    ``<path>.salvage.json`` sidecar so the drop is auditable, and returns
    the report. On interior corruption: raises ``JournalCorruptError``
    with the offset — never a silent skip that forks history."""
    if scan is None:
        scan = scan_journal(path)
    if scan.clean:
        return None
    if scan.interior:
        raise JournalCorruptError(
            f"journal {path!r} has a corrupt INTERIOR record at byte "
            f"offset {scan.bad_offset} (line {scan.bad_line_no}, "
            f"{scan.bad_reason}); refusing to replay past it — a silent "
            "skip would fork history. Recover from a replica, or follow "
            "docs/durability.md#corrupt-journal-runbook "
            "(inspect with `python -m ai4e_tpu.taskstore.journal "
            f"{path}`)",
            offset=scan.bad_offset, line_no=scan.bad_line_no,
            reason=scan.bad_reason or "checksum")
    report = SalvageReport(
        path=path, truncated_at=scan.good_bytes,
        dropped_bytes=scan.tail_bytes,
        reason=scan.bad_reason or "torn",
        records_kept=scan.records, chain_head=scan.chain_head)
    with open(path, "rb+") as fh:
        fh.truncate(scan.good_bytes)
    try:
        import time
        report_path = path + ".salvage.json"
        doc = dict(report.to_dict(), ts=time.time())
        with open(report_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
    except OSError:
        # The truncation (the correctness half) already happened; a
        # failed audit sidecar must not block boot.
        import logging
        logging.getLogger("ai4e_tpu.taskstore").exception(
            "could not write salvage report beside %s", path)
    return report


# -- fsync policy ------------------------------------------------------------

# AI4E_TASKSTORE_FSYNC (docs/durability.md): how hard an acknowledged
# append is pushed toward the platter before the caller unblocks.
#   never      — write+flush only (the page cache); survives process
#                SIGKILL, loses the unsynced tail on a machine crash.
#                Today's behavior, the default.
#   always     — fsync per append; an acknowledged mutation survives a
#                machine crash.
#   group:<ms> — group commit: at most one fsync per window, piggybacked
#                on appends and completed by a timer, so the crash
#                window is bounded by <ms> while the fsync cost
#                amortizes over every append in the window.
FSYNC_ENV = "AI4E_TASKSTORE_FSYNC"


def parse_fsync_policy(raw: str | None) -> tuple[str, float]:
    """``(kind, group_interval_s)``; raises ValueError loudly on junk so a
    typo'd policy fails at construction, not as silent data loss."""
    if raw is None:
        raw = os.environ.get(FSYNC_ENV, "") or "never"
    value = raw.strip().lower()
    if value in ("", "never"):
        return "never", 0.0
    if value == "always":
        return "always", 0.0
    if value.startswith("group:"):
        try:
            ms = float(value[len("group:"):])
        except ValueError:
            ms = -1.0
        # NOT `ms <= 0`: NaN compares False both ways and inf parses —
        # either would construct a store whose group fsync silently
        # never fires (the exact silent data loss this parser exists to
        # refuse).
        if not (0 < ms < float("inf")):
            raise ValueError(
                f"bad fsync policy {raw!r}: group:<ms> needs a positive "
                "finite millisecond window (e.g. group:20)")
        return "group", ms / 1000.0
    raise ValueError(
        f"bad fsync policy {raw!r}; expected never | always | group:<ms> "
        f"({FSYNC_ENV})")


# -- offline verification CLI ------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    """``python -m ai4e_tpu.taskstore.journal <path> [...]`` — verify
    journals offline: per-file verdict, record/legacy counts, chain head,
    and the exact offset of the first bad record. Exit 1 on any corrupt
    file (torn tails report salvageable and exit 0 — boot repairs them)."""
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m ai4e_tpu.taskstore.journal "
              "<journal-path> [...]", file=sys.stderr)
        return 2
    rc = 0
    for path in argv:
        try:
            scan = scan_journal(path)
        except OSError as exc:
            print(f"{path}: unreadable ({exc})")
            rc = 1
            continue
        if scan.clean:
            print(f"{path}: OK — {scan.records} records "
                  f"({scan.legacy_records} legacy), "
                  f"chain head {scan.chain_head}")
        elif not scan.interior:
            print(f"{path}: TORN TAIL at byte {scan.bad_offset} "
                  f"(line {scan.bad_line_no}, {scan.bad_reason}); "
                  f"{scan.records} records verified, salvage will drop "
                  f"{scan.tail_bytes} bytes — boot repairs this")
        else:
            print(f"{path}: CORRUPT interior record at byte "
                  f"{scan.bad_offset} (line {scan.bad_line_no}, "
                  f"{scan.bad_reason}); {scan.records} records verified "
                  "before it — see "
                  "docs/durability.md#corrupt-journal-runbook")
            rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover - exercised via main()
    raise SystemExit(main())
