"""Sharded task store — N independent shards over one consistent-hash ring.

ROADMAP item 3 ("million-user control plane"): one primary store + passive
replicas is both the availability ceiling (any primary death stalls the
WHOLE keyspace until failover) and the scale ceiling (every journal byte
funnels through one lock, one fsync stream). This module shards the task
keyspace so the loss of any one shard primary degrades 1/N of the keyspace
for the duration of a promotion, and the other N-1 shards never notice.

Layout (Redis-Cluster-style consistent hashing over a fixed slot space):

- ``ShardRing`` — TaskId → hash slot (stable BLAKE2 digest, never Python's
  per-process ``hash``) → owning shard via a slot table. A fixed slot
  space makes a *keyspace range* a first-class thing: a live rebalance is
  "move slot S from shard A to shard B", not a re-hash of the world.
- ``ShardGroup`` — one shard's primary (journaled, epoch-fenced — the
  same ``FollowerTaskStore`` machinery the whole-store HA pair uses, per
  shard) plus passive replicas absorbing the primary's journal through
  ``ShardReplicaLink``. SIGKILL of the primary → ``fail_over`` drains the
  durable journal tail into a replica, promotes it (minting the next
  fencing epoch), and the facade re-routes — writes refuse on the corpse
  (``StoreClosedError``), never half-apply.
- ``ShardedTaskStore`` — the facade the rest of the platform holds where
  it used to hold one store. Every single-store assumption becomes a
  ring lookup; aggregate queries (depths, endpoints, snapshots) fan out;
  listeners fan in through one relay per shard, which also publishes
  terminal transitions to that shard's ``ShardChangeFeed`` (``feed.py``)
  so ~100k long-poll watchers ride N feed attachments.

Split-brain is structurally prevented, per shard and across rebalance:

- **failover**: the promoted replica's ``promote()`` mints a journaled
  epoch strictly above everything the dead primary ever wrote, and the
  dead primary's store refuses all mutations (closed) — the same fence
  the whole-store HA pair proves in ``tests/test_fencing.py``, now per
  shard;
- **rebalance**: the ring flip happens while holding the OLD owner's
  store lock, and every shard store re-checks ring ownership under its
  own lock on every mutation (``InMemoryTaskStore._check_owner`` →
  ``NotOwnerError``). A write that routed to the old owner before the
  flip blocks on that same lock and is refused after it; the facade
  re-routes it to the new owner, which received the full range (bulk
  copy + an atomic delta while the old owner was frozen) BEFORE the flip
  became visible. The interleaving regression in
  ``tests/test_race_regressions.py`` explores exactly this window.

Residual windows (stated, not hidden — docs/sharding.md):

- memory-only records (``durable=False`` cache hits) do not migrate; a
  moved cache-hit TaskId 404s afterwards, the same contract as a restart;
- a rebalanced task's already-enqueued broker message stays on the old
  shard's sub-queue; its delivery still routes every store write through
  the ring, so placement is stale for one delivery but correctness holds;
- replicas re-arm after a failover the way the whole-store pair does:
  the promoted store runs without a standby until the deployment
  provisions one.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import threading
from typing import Callable, Iterable

from .feed import ShardChangeFeed
from .journal import JournalCorruptError
from .replication import split_complete_lines
from .store import (FollowerTaskStore, InMemoryTaskStore,
                    JournalDegradedError, NotOwnerError, NotPrimaryError,
                    StoreClosedError, TaskNotFound)
from .task import APITask, new_task_id

log = logging.getLogger("ai4e_tpu.taskstore.sharding")


def stable_hash(task_id: str) -> int:
    """Process-independent TaskId hash (BLAKE2b-64). Python's ``hash`` is
    salted per process — two control-plane processes would disagree on
    ownership of every task."""
    return int.from_bytes(
        hashlib.blake2b(task_id.encode("utf-8"), digest_size=8).digest(),
        "big")


class ShardRing:
    """TaskId → slot → shard, with atomic single-slot reassignment.

    The slot table is the consistent-hash structure made explicit (the
    Redis Cluster / 16384-hash-slots shape): adding capacity or rebalancing
    moves whole slots, and only the moved slots' keys change owner —
    everything else is untouched. ``version`` increments on every
    reassignment: the rebalance epoch a stale owner's fence re-checks."""

    def __init__(self, shards: int, slots: int = 64):
        if shards < 1:
            raise ValueError("shards must be >= 1")
        if slots < shards:
            raise ValueError(f"slots ({slots}) must be >= shards ({shards})")
        self.shards = shards
        self.slots = slots
        self._assign = [i % shards for i in range(slots)]
        self.version = 0
        self._lock = threading.Lock()

    def slot_for(self, task_id: str) -> int:
        return stable_hash(task_id) % self.slots

    def shard_for(self, task_id: str) -> int:
        return self._assign[self.slot_for(task_id)]

    def shard_of_slot(self, slot: int) -> int:
        return self._assign[slot]

    def slots_of(self, shard: int) -> list[int]:
        return [s for s, owner in enumerate(self._assign) if owner == shard]

    def assign(self, slot: int, shard: int) -> None:
        """Reassign one slot. The caller (``move_slot``) holds the OLD
        owner's store lock around this, which is what makes the flip
        atomic with respect to that store's write fence."""
        if not 0 <= shard < self.shards:
            raise ValueError(f"shard {shard} out of range")
        with self._lock:
            self._assign[slot] = shard
            self.version += 1

    def assignments(self) -> list[int]:
        return list(self._assign)


class ShardReplicaLink:
    """One passive replica's journal tail — the in-process analogue of
    ``replication.JournalReplicator``, reading the primary's journal FILE
    (which outlives the primary: it is the shard's durable truth) instead
    of the HTTP stream. Same consume-whole-lines rule, same generation
    resync contract (a compaction rewrite restarts the reader at offset 0
    of what is then a full snapshot).

    **Wire mode** (``primary_url=``): the same link absorbing the same
    protocol over the socket — ``GET /v1/taskstore/journal`` with the
    offset/generation/limit contract ``replication.py`` defines — for a
    standby living in a DIFFERENT process than its shard primary (the
    multi-process rig, ``ai4e_tpu/rig/``). Checksum/chain verification,
    the corrupt-line park, and the generation resync behave identically
    to file mode; what changes is reach (any host) and the failover
    drain (a dead primary's HTTP stream is unreachable, so a same-host
    rig drains the journal *file* instead — ``absorb_journal_file``).
    Fetches are synchronous (urllib) by design: ``sync_once`` is sync
    absorb work and event-loop callers already wrap it in
    ``asyncio.to_thread``."""

    def __init__(self, group: "ShardGroup | None", standby: FollowerTaskStore,
                 primary_url: str | None = None, api_key: str | None = None,
                 wire_timeout: float = 10.0,
                 chunk_limit: int = 4 * 1024 * 1024):
        if group is None and primary_url is None:
            raise ValueError("a ShardReplicaLink needs a group (file mode) "
                             "or a primary_url (wire mode)")
        self.group = group
        self.standby = standby
        self.primary_url = primary_url.rstrip("/") if primary_url else None
        self._wire_headers = ({"Ocp-Apim-Subscription-Key": api_key}
                              if api_key else {})
        self._wire_timeout = wire_timeout
        self._chunk_limit = chunk_limit
        # For log lines in wire mode (no group to name the shard).
        self.shard_index = group.index if group is not None else -1
        self.generation = -1
        self.offset = 0
        self._buffer = b""
        # (generation, offset) this link is PARKED at after a verified
        # journal line failed its checksum/chain (the file's bytes will
        # not change — re-reading re-fails): the verified prefix stays
        # absorbed, progress stops loudly, and a failover drain promotes
        # on that prefix — torn-tail semantics. A compaction rewrite
        # (generation bump) clears the park.
        self._corrupt_at: tuple[int, int] | None = None
        # Serializes tail-loop polls (executor thread) against the failover
        # drain (caller's thread): both advance offset/_buffer through
        # sync_once, and interleaving them would double-absorb or skip
        # lines.
        self._sync_lock = threading.Lock()

    def sync_once(self) -> int:
        """Absorb any new journal bytes; returns bytes consumed (0 = caught
        up). Synchronous file work — callers on an event loop wrap it in
        ``asyncio.to_thread`` (the replicator absorbs the same way)."""
        with self._sync_lock:
            if self.primary_url is not None:
                return self._sync_once_wire()
            return self._sync_once_locked()

    # -- wire mode ----------------------------------------------------------

    def _fetch_wire(self, limit: int) -> tuple[int, int, int, bytes]:
        """One journal-stream poll: ``(generation, served_from, size,
        chunk)``. Raises ``OSError`` when the primary is unreachable (the
        tail loop retries; a failover drain gives up and the rig falls
        back to the journal file)."""
        import urllib.error
        import urllib.parse
        import urllib.request

        from .replication import JOURNAL_PATH
        params = urllib.parse.urlencode({
            "offset": str(self.offset),
            "generation": str(self.generation),
            "wait": "0",
            "limit": str(limit),
            # Fencing evidence, same as the HTTP replicator: a link that
            # outlived a failover demotes the deposed primary it polls.
            "epoch": str(self.standby.epoch)})
        req = urllib.request.Request(
            f"{self.primary_url}{JOURNAL_PATH}?{params}",
            headers=self._wire_headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=self._wire_timeout) as resp:
                gen = int(resp.headers.get("X-Journal-Generation", "0"))
                served_from = int(resp.headers.get("X-Journal-Offset",
                                                   str(self.offset)))
                size = int(resp.headers.get("X-Journal-Size", "0"))
                chunk = resp.read()
        except urllib.error.HTTPError as exc:
            raise OSError(
                f"journal stream at {self.primary_url} answered "
                f"HTTP {exc.code}") from exc
        return gen, served_from, size, chunk

    def _sync_once_wire(self) -> int:
        parked = self._corrupt_at == (self.generation, self.offset)
        # While parked, probe with a 1-byte limit: the only thing that can
        # clear a park is a generation bump (compaction rewrote the bytes),
        # and re-reading the primary's ever-growing unabsorbed suffix every
        # poll is the cost the file mode's pre-open check avoids.
        gen, served_from, size, chunk = self._fetch_wire(
            1 if parked else self._chunk_limit)
        if gen != self.generation or served_from != self.offset:
            if served_from != 0:
                # The server restarts mismatched readers at 0; anything
                # else is a contract violation (replication.py).
                raise OSError(
                    f"journal reset served from offset {served_from}")
            if self.generation != -1:
                log.info("shard %d wire replica: journal generation "
                         "%d -> %d; resyncing", self.shard_index,
                         self.generation, gen)
            self.standby.reset()
            self._buffer = b""
            self.generation = gen
            self.offset = 0
            self._corrupt_at = None
            if parked and size > len(chunk):
                # A parked probe's 1-byte limit truncated the resync
                # chunk; drop it and let the next poll read full-width.
                chunk = b""
            parked = False
        if parked or not chunk:
            return 0
        lines, self._buffer = split_complete_lines(self._buffer + chunk)
        if lines:
            try:
                self.standby.absorb_lines(lines)
            except JournalCorruptError as exc:
                self._corrupt_at = (self.generation, self.offset)
                self._buffer = b""
                log.error(
                    "shard %d wire replica: journal line failed "
                    "verification at ~offset %d of %s (%s); replica parks "
                    "on the verified prefix until the journal is repaired "
                    "or compacted (docs/durability.md)", self.shard_index,
                    self.offset, self.primary_url, exc)
                return 0
        self.offset += len(chunk)
        return len(chunk)

    # -- file mode ----------------------------------------------------------

    def _sync_once_locked(self) -> int:
        primary = self.group.primary
        # Generation + open under the primary's lock: compaction swaps the
        # file under that lock (http.py journal_stream does the same). A
        # dead primary's lock is uncontended and its generation frozen.
        with primary._lock:
            gen = primary.journal_generation
            if self._corrupt_at == (gen, self.offset):
                # Parked on a verified-corrupt record of THIS generation;
                # the bytes cannot heal in place. Checked before any
                # open/read — a parked link must not re-read the primary's
                # ever-growing unabsorbed suffix on every tail poll
                # (review finding). A compaction rewrite (generation
                # bump) clears the park; a failover drain stops here on
                # the verified prefix.
                return 0
            try:
                fh = open(self.group.journal_path, "rb")
            except FileNotFoundError:
                return 0
        try:
            if gen != self.generation:
                if self.generation != -1:
                    log.info("shard %d replica: journal generation %d -> %d;"
                             " resyncing", self.group.index, self.generation,
                             gen)
                self.standby.reset()
                self._buffer = b""
                self.generation = gen
                self.offset = 0
                # A park belongs to the generation it was observed in; a
                # stale tuple could otherwise match a fresh (gen, offset)
                # pair and silently stall a healthy replica forever.
                self._corrupt_at = None
            fh.seek(self.offset)
            chunk = fh.read()
        finally:
            fh.close()
        if not chunk:
            return 0
        lines, self._buffer = split_complete_lines(self._buffer + chunk)
        if lines:
            try:
                self.standby.absorb_lines(lines)
            except JournalCorruptError as exc:
                # absorb applied the verified prefix and refused the bad
                # line. Park the link (never absorb it silently — that
                # would ratify the primary's bit-rot on the replica too);
                # the un-absorbed suffix re-absorbs idempotently if the
                # generation ever changes.
                self._corrupt_at = (self.generation, self.offset)
                self._buffer = b""
                log.error(
                    "shard %d replica: journal line failed verification "
                    "at ~offset %d of %s (%s); replica parks on the "
                    "verified prefix until the journal is repaired or "
                    "compacted (docs/durability.md)", self.group.index,
                    self.offset, self.group.journal_path, exc)
                return 0
        self.offset += len(chunk)
        return len(chunk)

    def drain(self) -> None:
        """Final catch-up before promotion: the primary is dead (no more
        appends — every acknowledged write was flushed before its caller
        returned), so reading to EOF yields its exact final state."""
        while self.sync_once():
            pass


def absorb_journal_file(standby: FollowerTaskStore, path: str) -> int:
    """Full resync of ``standby`` from a journal FILE — the failover drain
    a wire-mode replica runs when its shard primary is DEAD: the HTTP
    stream died with the process, but the journal file is the shard's
    durable truth and (on a shared filesystem — the rig's one-host case)
    still holds every acknowledged write. Reset-and-replay from offset 0
    is always correct, exactly the HTTP replicator's reconnect contract:
    the wire link's byte offset belongs to a generation the reader can no
    longer verify against a live server, so no tail-continuation is
    attempted. Whole lines only — an unterminated torn tail is left
    behind, torn-tail semantics. Returns lines absorbed. A
    ``JournalCorruptError`` mid-file leaves the verified prefix applied
    and re-raises: the caller decides whether to promote on the prefix
    (the park contract) or refuse."""
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return 0
    lines, _tail = split_complete_lines(data)
    standby.reset()
    if lines:
        standby.absorb_lines(lines)
    return len(lines)


class ShardGroup:
    """One shard: primary + passive replicas + failover bookkeeping."""

    def __init__(self, index: int, journal_path: str | None = None,
                 replicas: int = 1, compact_every: int = 5000,
                 store_kwargs: dict | None = None):
        self.index = index
        kw = dict(store_kwargs or {})
        self.links: list[ShardReplicaLink] = []
        if journal_path:
            self.journal_path = f"{journal_path}.shard{index}"
            self.primary: InMemoryTaskStore = FollowerTaskStore(
                self.journal_path, start_as_primary=True,
                compact_every=compact_every, **kw)
            for j in range(replicas):
                standby = FollowerTaskStore(
                    f"{self.journal_path}.replica{j}",
                    compact_every=compact_every, **kw)
                self.links.append(ShardReplicaLink(self, standby))
        else:
            # Journal-less shards scale the keyspace but cannot fail over
            # (nothing durable to promote from) — the same durability
            # trade the unsharded in-memory store already makes. The
            # journal-only knobs (fsync policy, journal metrics) have
            # nothing to attach to here.
            kw.pop("fsync", None)
            kw.pop("metrics", None)
            self.journal_path = None
            self.primary = InMemoryTaskStore(**kw)
        self.active: InMemoryTaskStore = self.primary
        self.dead = False
        self._lock = threading.Lock()

    @property
    def epoch(self) -> int:
        return getattr(self.active, "epoch", 0)

    def mark_dead(self) -> None:
        """SIGKILL semantics for the chaos harness: the primary's journal
        handle closes and every subsequent mutation refuses with
        ``StoreClosedError`` — no further writes are acknowledged, exactly
        the window a real process kill leaves. The journal FILE survives
        (it is the shard's durable truth) for the replica's final drain."""
        self.active.close()
        self.dead = True

    def close(self) -> None:
        self.active.close()
        for link in self.links:
            link.standby.close()


class ShardedTaskStore:
    """The facade the platform holds where it used to hold one store.

    Same verb surface as ``InMemoryTaskStore`` (plus the HA extras the
    assembly duck-types): per-TaskId verbs route by ring lookup with
    bounded re-route on ``NotOwnerError`` (rebalance) and inline failover
    promotion on ``StoreClosedError`` (shard primary death); aggregate
    queries fan out; listeners and the publisher fan in/out through one
    relay per shard."""

    # Bounded re-route: one rebalance flip or one failover per attempt;
    # anything needing more than this many is a real fault to surface.
    _ROUTE_ATTEMPTS = 4

    def __init__(self, shards: int, slots: int = 64,
                 journal_path: str | None = None, replicas: int = 1,
                 tail_interval: float = 0.25, feed_recent: int = 4096,
                 compact_every: int = 5000, result_backend=None,
                 result_offload_threshold: int | None = None,
                 fsync: str | None = None, metrics=None):
        self.ring = ShardRing(shards, slots=slots)
        store_kwargs = dict(result_backend=result_backend,
                            result_offload_threshold=result_offload_threshold,
                            fsync=fsync, metrics=metrics)
        self.groups = [
            ShardGroup(i, journal_path=journal_path, replicas=replicas,
                       compact_every=compact_every,
                       store_kwargs=store_kwargs)
            for i in range(shards)]
        self.feeds = [ShardChangeFeed(i, recent=feed_recent)
                      for i in range(shards)]
        self.tail_interval = tail_interval
        self._listeners: list[Callable[[APITask], None]] = []
        self._publisher = None
        self._rebalance_lock = threading.Lock()
        self._tail_tasks: list[asyncio.Task] = []
        self._tail_stop: asyncio.Event | None = None
        for group in self.groups:
            self._adopt(group.active, group.index)

    # -- shard adoption (fence + publisher + listener relay) ---------------

    def _adopt(self, store: InMemoryTaskStore, index: int) -> None:
        """Wire one store in as shard ``index``'s active primary. The relay
        is attached HERE — never to standbys, whose absorb-path
        notifications would duplicate every event the primary already
        relayed."""
        store.set_write_fence(
            lambda task_id, _i=index: self.ring.shard_for(task_id) == _i)
        store.set_publisher(self._publish)
        store.add_listener(
            lambda task, _i=index: self._relay(task, _i))

    def _publish(self, task: APITask) -> None:
        if self._publisher is not None:
            self._publisher(task)

    def _relay(self, task: APITask, shard_index: int) -> None:
        # Mirror StoreSideEffects._notify's isolation: one listener's
        # failure must not starve the rest (or the feed).
        for listener in self._listeners:
            try:
                listener(task)
            except Exception:  # noqa: BLE001 — observers must not break the store
                log.exception("sharded-store listener failed for %s",
                              task.task_id)
        try:
            # Feed of the task's CURRENT ring owner, not the notifying
            # shard: a watcher parks on feed_for(task_id), and a terminal
            # transition applied by the old owner in the same instant a
            # rebalance lands must reach the feed that watcher chose.
            self.feeds[self.ring.shard_for(task.task_id)].publish(task)
        except Exception:  # noqa: BLE001 — same isolation as above
            log.exception("shard feed publish failed for %s", task.task_id)

    # -- routing core -------------------------------------------------------

    def shard_for(self, task_id: str) -> int:
        """Owning shard index — also the broker's sub-queue router."""
        return self.ring.shard_for(task_id)

    def feed_for(self, task_id: str) -> ShardChangeFeed:
        """The owning shard's change feed (gateway long-poll attaches
        here — N feeds serve every watcher)."""
        return self.feeds[self.ring.shard_for(task_id)]

    def shard_stores(self) -> list[InMemoryTaskStore]:
        """Active per-shard stores, for per-shard SCANS (the reaper). All
        per-task ACTIONS must still route through the facade — a direct
        write to a scanned store is exactly the stale-owner hazard the
        fence exists to refuse."""
        return [g.active for g in self.groups]

    def _route(self, task_id: str, op):
        """Run ``op(store)`` against the owning shard, re-routing across a
        concurrent rebalance and promoting through a dead primary. Reads
        are fenced too, by outcome rather than by lock: a miss (raise or
        None) answered by a store the ring no longer points at may be the
        handoff window — the moved range was forgotten there — so a miss
        only stands when the answering store is STILL the owner."""
        last: Exception | None = None
        for _ in range(self._ROUTE_ATTEMPTS):
            group = self.groups[self.ring.shard_for(task_id)]
            if group.dead and not self._fail_over(group):
                # No replica to promote: surface the dead shard loudly
                # rather than serving from a corpse.
                raise StoreClosedError(
                    f"shard {group.index} primary is dead and has no "
                    "promotable replica")
            try:
                result = op(group.active)
            except NotOwnerError as exc:
                # Rebalance flipped ownership between our ring lookup and
                # the store's fence check; a fresh lookup finds the new
                # owner (which imported the full range before the flip).
                last = exc
                continue
            except TaskNotFound:
                if self.groups[self.ring.shard_for(task_id)] is not group:
                    # The slot moved while we were asking: the task was
                    # forgotten HERE but lives on the new owner — a 404 to
                    # the client would be a lie. Re-route.
                    continue
                raise
            except (StoreClosedError, NotPrimaryError) as exc:
                last = exc
                if not self._fail_over(group):
                    raise
                continue
            except JournalDegradedError as exc:
                # Disk fault on the shard primary (ENOSPC/EIO): it is
                # fenced read-only — for the sharded facade that is a
                # dead writer WHEN a replica can take over. Only then is
                # it closed (journal handle released; the FILE holds
                # every acknowledged write for the drain) and promoted
                # over. With NO promotable replica the primary must stay
                # open: it is still serving reads and is recover()able —
                # closing it would convert a transient disk fault into a
                # permanent full-shard outage (review finding). The typed
                # degraded error surfaces instead, so the HTTP layer
                # answers the 503 + X-Shed-Reason: journal-degraded
                # contract.
                if not group.dead and not group.links:
                    raise
                last = exc
                if not group.dead:
                    log.error(
                        "shard %d: primary is journal-degraded (%s); "
                        "failing over to a replica", group.index, exc)
                    group.mark_dead()
                if not self._fail_over(group):
                    raise
                continue
            if (result is None
                    and self.groups[self.ring.shard_for(task_id)]
                    is not group):
                # None-shaped miss (get_result/open_result, a conditional
                # verb's refusal) from a store that lost the slot mid-call:
                # the new owner holds the migrated state — ask it. The
                # conditional verbs are safe to re-run: they re-check their
                # condition against the migrated state.
                continue
            return result
        raise StoreClosedError(
            f"could not route task {task_id!r} after "
            f"{self._ROUTE_ATTEMPTS} attempts") from last

    # -- failover -----------------------------------------------------------

    def _fail_over(self, group: ShardGroup) -> bool:
        """Promote a replica over a dead shard primary. Returns True when
        the group has a live active store on exit (this call promoted, or
        another thread already had). Sequence mirrors the whole-store
        watchdog: drain the durable journal tail first (zero loss — every
        acknowledged write was flushed), promote (minting the fencing
        epoch), and only then adopt + swap, so no write lands on the
        standby before it holds the full state."""
        with group._lock:
            if not group.dead:
                return True
            standby = None
            while group.links:
                link = group.links.pop(0)
                candidate = link.standby
                try:
                    link.drain()
                except Exception:  # noqa: BLE001 — promote anyway: the standby holds its last-absorbed state, and refusing leaves the shard with NO writer
                    log.exception(
                        "shard %d: final journal drain failed; promoting "
                        "the replica on its last absorbed state",
                        group.index)
                try:
                    candidate.promote()
                except JournalDegradedError as exc:
                    # The STANDBY's own disk faulted minting the fencing
                    # epoch: promote() unwound it to an intact (degraded)
                    # follower. Letting the error escape here would both
                    # abort the failover AND silently discard the popped
                    # replica (review finding) — instead try the next
                    # one; with none left the shard is loudly writer-less
                    # (False → the caller's StoreClosedError).
                    log.error(
                        "shard %d: replica's journal disk faulted during "
                        "promotion (%s); trying the next replica",
                        group.index, exc)
                    continue
                standby = candidate
                break
            if standby is None:
                return False
            self._adopt(standby, group.index)
            group.primary = standby
            # Remaining replicas (replicas > 1) must re-home onto the NEW
            # primary's journal file and resync from its snapshot — their
            # offsets into the dead primary's file mean nothing there.
            group.journal_path = getattr(standby, "_journal_path",
                                         group.journal_path)
            for other in group.links:
                other.generation = -1
            group.active = standby
            group.dead = False
            log.warning(
                "shard %d: primary dead; promoted replica at fencing "
                "epoch %d", group.index, standby.epoch)
            return True

    # -- replication lifecycle ----------------------------------------------

    async def start_replication(self) -> None:
        """Start every replica's journal tail loop on the running loop."""
        self._tail_stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for group in self.groups:
            for link in group.links:
                self._tail_tasks.append(
                    loop.create_task(self._tail(link)))

    async def _tail(self, link: ShardReplicaLink) -> None:
        stop = self._tail_stop
        while not stop.is_set():
            try:
                await asyncio.to_thread(link.sync_once)
            except RuntimeError:
                # absorb-after-promote / reset-after-promote: this standby
                # was promoted out from under its tail loop — done.
                return
            except Exception:  # noqa: BLE001 — keep tailing through transient I/O errors
                log.exception("shard %d replica tail failed; retrying",
                              link.group.index)
            try:
                await asyncio.wait_for(stop.wait(), self.tail_interval)
                return
            except asyncio.TimeoutError:
                continue

    async def stop_replication(self) -> None:
        if self._tail_stop is not None:
            self._tail_stop.set()
        for task in self._tail_tasks:
            task.cancel()
        for task in self._tail_tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001; ai4e: noqa[AIL005] — awaiting our own cancelled loops; the outcome is irrelevant at teardown
                pass
        self._tail_tasks = []

    # -- live rebalance -----------------------------------------------------

    def move_slot(self, slot: int, dest_index: int) -> int:
        """Move one hash slot's keyspace range to ``dest_index`` under load;
        returns tasks moved. Two phases:

        1. **bulk copy** — export the range (brief source lock), import on
           the destination; traffic keeps flowing to the source;
        2. **atomic handoff** — under the SOURCE's store lock: export the
           delta (records that changed since the copy — object identity,
           every mutation replaces the record object), import it on the
           destination (nested dest lock; the only place two shard locks
           nest, always source→dest, so no cycle), flip the ring, and
           forget the range on the source. The source's write fence checks
           ownership under this same lock, so a concurrent write either
           lands before the flip (and is exported in the delta) or is
           refused after it and re-routed by the facade.
        """
        if not 0 <= slot < self.ring.slots:
            raise ValueError(f"slot {slot} out of range")
        with self._rebalance_lock:
            src_index = self.ring.shard_of_slot(slot)
            if src_index == dest_index:
                return 0
            # The whole move retries across a shard failover landing mid
            # migration: phase 2 re-verifies (under the source lock) that
            # the stores it snapshot are still the shards' active stores —
            # a promotion swapped one out means the snapshot (or the
            # import target) is a corpse's frozen state, and proceeding
            # would flip the ring onto a copy missing the promoted
            # store's writes.
            last: Exception | None = None
            for _attempt in range(3):
                moved = self._try_move_slot(slot, src_index, dest_index)
                if moved is not None:
                    return moved
                last = StoreClosedError(
                    f"shard store swapped mid-rebalance of slot {slot}")
            raise StoreClosedError(
                f"rebalance of slot {slot} kept racing shard failovers"
            ) from last

    def _try_move_slot(self, slot: int, src_index: int,
                       dest_index: int) -> int | None:
        """One migration attempt; None = a failover swapped a store mid
        copy and the caller should retry (the bulk copy is re-imported
        idempotently over the stale one)."""
        # Both ends must be live writers: a dead source would explode at
        # the forget (after the copy), a dead destination at the import —
        # fail over first, or refuse up front.
        for group in (self.groups[src_index], self.groups[dest_index]):
            if group.dead and not self._fail_over(group):
                raise StoreClosedError(
                    f"shard {group.index} primary is dead with no "
                    "promotable replica; cannot rebalance")
        src = self.groups[src_index].active
        dest = self.groups[dest_index].active
        # Phase 1: bulk copy. Snapshot record/result object identities
        # for delta detection — every store mutation replaces the
        # stored object, so `is` comparison is exact.
        with src._lock:
            ids1 = self._slot_ids(src, slot)
            tasks1 = {tid: src._tasks[tid] for tid in ids1}
            results1 = {}
            for tid in ids1:
                for key in src._result_keys.get(tid, ()):
                    results1[key] = src._results.get(key)
            recs1 = src.export_task_records(ids1)
        try:
            dest.import_task_records(recs1)
        except (StoreClosedError, NotPrimaryError):
            return None  # destination died mid-copy; retry fails it over
        except JournalDegradedError:
            # Destination's disk faulted mid-import: same as a death for
            # rebalance purposes — mark it so the retry fails it over to
            # a replica before re-copying.
            self.groups[dest_index].mark_dead()
            return None
        # Phase 2: atomic handoff under the source lock. Until the ring
        # flips, the range transiently exists on BOTH shards (aggregate
        # queries briefly double-count it — docs/sharding.md residual
        # windows); a failure BEFORE the flip rolls the phase-1 copy
        # back off the destination so nothing double-counts forever.
        flipped = False
        try:
            with src._lock:
                if (self.groups[src_index].active is not src
                        or self.groups[dest_index].active is not dest
                        or self.groups[src_index].dead
                        or self.groups[dest_index].dead):
                    # A promotion swapped a store between the phases.
                    # ``close()`` serializes on the store lock, so once
                    # this check passes the SOURCE cannot die before the
                    # handoff completes; the stale phase-1 copy is either
                    # on a corpse (dest swapped — irrelevant) or will be
                    # re-imported from the promoted source on retry.
                    return None
                ids2 = self._slot_ids(src, slot)
                delta_ids = [tid for tid in ids2
                             if tasks1.get(tid) is not src._tasks[tid]]
                delta = src.export_task_records(delta_ids)
                delta_set = set(delta_ids)
                for tid in ids2:
                    if tid in delta_set:
                        continue  # its results rode the full re-export
                    for key in src._result_keys.get(tid, ()):
                        cur = src._results.get(key)
                        if (results1.get(key) is not cur
                                and cur is not None):
                            delta.append(src._result_record(
                                key, cur[0], cur[1]))
                dest.import_task_records(delta)
                alive = set(ids2)
                evicted_between = [tid for tid in ids1
                                   if tid not in alive]
                if evicted_between:
                    # Evicted on the source AFTER the bulk copy (the
                    # retention sweep): the destination must not keep
                    # the phase-1 replica, or a task a client already
                    # saw 404 would resurrect once the ring flips.
                    dest.forget_tasks(evicted_between)
                self.ring.assign(slot, dest_index)
                flipped = True
                src.forget_tasks(ids2)
        except BaseException:
            if not flipped:
                # The ring never moved: undo the bulk copy or the
                # destination keeps (and journals, and replays) an
                # orphan replica of a range it does not own.
                try:
                    dest.forget_tasks(ids1)
                except Exception:  # noqa: BLE001 — best-effort rollback; the raise below carries the real fault
                    log.exception(
                        "rebalance rollback of slot %d on shard %d "
                        "failed; orphan copies may double-count until "
                        "retention evicts them", slot, dest_index)
            else:
                # Flipped but the source cleanup failed: ownership is
                # correct (fence blocks stale writes); the leftovers
                # are garbage the terminal-retention sweep collects.
                log.exception(
                    "rebalance of slot %d: source forget failed after "
                    "the flip; stale (fenced) copies remain on shard "
                    "%d until retention evicts them", slot, src_index)
            raise
        # The moved range's future transitions publish to the DESTINATION
        # feed now: stale terminal records in the source feed's replay map
        # would outlive any redrive of these tasks (and answer a long-poll
        # with the previous run's record if the slot ever moves back).
        self.feeds[src_index].invalidate(set(ids1) | set(ids2))
        moved = len(ids2)
        log.info("rebalanced slot %d: shard %d -> %d (%d tasks, ring "
                 "version %d)", slot, src_index, dest_index, moved,
                 self.ring.version)
        return moved

    def _slot_ids(self, store: InMemoryTaskStore, slot: int) -> list[str]:
        # Caller holds store._lock. O(shard's tasks); a per-slot index
        # would make this O(range) — not needed at current scale
        # (docs/sharding.md).
        return [tid for tid in store._tasks
                if self.ring.slot_for(tid) == slot]

    # -- store verb surface (per-task: ring-routed) ------------------------

    def upsert(self, task: APITask) -> APITask:
        if not task.task_id:
            # Mint here, not in the shard store: the id IS the routing key.
            task.task_id = new_task_id()
        return self._route(task.task_id, lambda s: s.upsert(task))

    def update_status(self, task_id: str, status: str,
                      backend_status: str | None = None) -> APITask:
        return self._route(
            task_id, lambda s: s.update_status(task_id, status,
                                               backend_status))

    def update_status_if(self, task_id: str, expected_status: str,
                         status: str,
                         backend_status: str | None = None) -> APITask | None:
        return self._route(
            task_id, lambda s: s.update_status_if(task_id, expected_status,
                                                  status, backend_status))

    def requeue_if(self, task_id: str, expected_status: str) -> APITask | None:
        return self._route(
            task_id, lambda s: s.requeue_if(task_id, expected_status))

    def get(self, task_id: str) -> APITask:
        return self._route(task_id, lambda s: s.get(task_id))

    def get_original_body(self, task_id: str) -> bytes:
        # The store's miss shape here is b"" (not a raise, not None) — map
        # it to None so _route's ownership re-check applies: an empty
        # answer from a store that just lost the slot must re-route to the
        # owner holding the migrated OrigHex, not stand as "no body".
        def op(store):
            body = store.get_original_body(task_id)
            return body if body else None

        return self._route(task_id, op) or b""

    def set_result(self, task_id: str, result: bytes,
                   content_type: str = "application/json",
                   stage: str | None = None) -> None:
        return self._route(
            task_id, lambda s: s.set_result(task_id, result,
                                            content_type=content_type,
                                            stage=stage))

    def set_result_ref(self, task_id: str,
                       content_type: str = "application/json",
                       stage: str | None = None) -> None:
        return self._route(
            task_id, lambda s: s.set_result_ref(task_id,
                                                content_type=content_type,
                                                stage=stage))

    def get_result(self, task_id: str,
                   stage: str | None = None) -> tuple[bytes, str] | None:
        return self._route(task_id,
                           lambda s: s.get_result(task_id, stage=stage))

    def open_result(self, task_id: str, stage: str | None = None):
        return self._route(task_id,
                           lambda s: s.open_result(task_id, stage=stage))

    def append_ledger(self, task_id: str, events: list[dict]) -> int:
        """Hop-ledger append, ring-routed like every per-TaskId mutation
        (observability/ledger.py). Residual: a rebalance moving the slot
        mid-flight leaves the already-stamped events on the old owner —
        acceptable for fail-open telemetry (docs/observability.md), the
        same contract as losing a timeline to a restart."""
        return self._route(task_id,
                           lambda s: s.append_ledger(task_id, events))

    def get_ledger(self, task_id: str) -> list[dict]:
        def op(store):
            # Empty → None so _route's ownership re-check applies (the
            # migrated timeline lives with the new owner when it moved
            # before any post-move stamp; see get_original_body).
            events = store.get_ledger(task_id)
            return events if events else None

        return self._route(task_id, op) or []

    # -- side-effect plumbing ----------------------------------------------

    def set_publisher(self, publisher) -> None:
        self._publisher = publisher

    def add_listener(self, listener: Callable[[APITask], None]) -> None:
        self._listeners.append(listener)

    # -- aggregate queries (fan-out) ---------------------------------------

    def set_len(self, endpoint_path: str, status: str) -> int:
        return sum(g.active.set_len(endpoint_path, status)
                   for g in self.groups)

    def set_members(self, endpoint_path: str, status: str) -> list[str]:
        out: list[str] = []
        for g in self.groups:
            out.extend(g.active.set_members(endpoint_path, status))
        return out

    def endpoints(self) -> list[str]:
        paths: set[str] = set()
        for g in self.groups:
            paths.update(g.active.endpoints())
        return sorted(paths)

    def depths(self) -> dict[str, dict[str, int]]:
        out: dict[str, dict[str, int]] = {}
        for g in self.groups:
            for path, counts in g.active.depths().items():
                agg = out.setdefault(path, {s: 0 for s in counts})
                for status, n in counts.items():
                    agg[status] = agg.get(status, 0) + n
        return out

    def snapshot(self) -> Iterable[APITask]:
        out: list[APITask] = []
        for g in self.groups:
            out.extend(g.active.snapshot())
        return out

    def unfinished_tasks(self) -> list[APITask]:
        out: list[APITask] = []
        for g in self.groups:
            out.extend(g.active.unfinished_tasks())
        return out

    def evict_terminal_older_than(self, age_s: float) -> int:
        return sum(g.active.evict_terminal_older_than(age_s)
                   for g in self.groups)

    @property
    def replayed_task_ids(self) -> set[str]:
        """Union of journal-restored ids across shards — the platform's
        restart re-seed reads this exactly as on the single store."""
        out: set[str] = set()
        for g in self.groups:
            out.update(getattr(g.active, "replayed_task_ids", ()) or ())
        return out

    def compact(self) -> None:
        for g in self.groups:
            compact = getattr(g.active, "compact", None)
            if compact is not None:
                compact()

    def close(self) -> None:
        for g in self.groups:
            g.close()

    # -- chaos / introspection ----------------------------------------------

    def kill_shard_primary(self, index: int) -> None:
        """Chaos hook: SIGKILL shard ``index``'s primary (see
        ``ShardGroup.mark_dead``). The next write routed there performs
        the failover promotion inline."""
        self.groups[index].mark_dead()

    def topology(self) -> dict:
        """Ring + per-shard role/epoch/feed state — the ``/v1/taskstore/
        shards`` endpoint's body."""
        return {
            "shards": self.ring.shards,
            "slots": self.ring.assignments(),
            "version": self.ring.version,
            "groups": [
                {"shard": g.index,
                 "epoch": g.epoch,
                 "dead": g.dead,
                 "replicas": len(g.links),
                 "journal": g.journal_path,
                 # Hash-chain heads (docs/durability.md): the primary's
                 # own-file head beside each replica's verified-stream
                 # head — divergence is a string comparison right here.
                 "chain_head": getattr(g.active, "chain_head", None),
                 "replica_chain_heads": [
                     link.standby.replica_chain_head for link in g.links],
                 "degraded": bool(getattr(g.active, "degraded", False)),
                 "feed_seq": self.feeds[g.index].seq,
                 "watchers": self.feeds[g.index].watcher_count}
                for g in self.groups],
        }

    def journal_stats(self) -> dict:
        """Aggregate per-shard journal stats (bench's ``journal`` block):
        sums across shards, max append p99, any-degraded."""
        shards = []
        for g in self.groups:
            stats = getattr(g.active, "journal_stats", None)
            if stats is not None:
                shards.append(stats())
        if not shards:
            return {}
        return {
            "bytes_appended": sum(s["bytes_appended"] for s in shards),
            "fsyncs": sum(s["fsyncs"] for s in shards),
            "compactions": sum(s["compactions"] for s in shards),
            "salvages": sum(s["salvages"] for s in shards),
            "fsync_policy": shards[0]["fsync_policy"],
            "append_p99_ms": max(s["append_p99_ms"] for s in shards),
            "degraded": any(s["degraded"] for s in shards),
            "per_shard_chain_heads": [s["chain_head"] for s in shards],
        }
