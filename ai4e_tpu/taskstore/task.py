"""Task record and status model.

The task record is the platform's only durable state: status, endpoint, and the
original request body persist outside workers so any replica can resume a task by
TaskId. Record shape mirrors the reference's ``APITask``
(``ProcessManager/Classes/APITask.cs:10-29``): TaskId, Timestamp, Status,
BackendStatus, Endpoint, Body, PublishToGrid, with a derived EndpointPath.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field, replace
from urllib.parse import urlparse


class TaskStatus:
    """Canonical lifecycle states (``CacheConnectorUpsert.cs:133-142`` keeps one
    sorted set per endpoint per state with exactly these names)."""

    CREATED = "created"
    RUNNING = "running"
    COMPLETED = "completed"
    FAILED = "failed"
    # Deadline-shed work (admission/): terminal like failed, but it is not
    # a platform failure — the request's budget ran out before execution
    # and the platform declined to burn device time on an answer nobody is
    # waiting for (docs/admission.md).
    EXPIRED = "expired"

    ALL = (CREATED, RUNNING, COMPLETED, FAILED, EXPIRED)
    TERMINAL = (COMPLETED, FAILED, EXPIRED)

    # The exact prose the platform writes when a task's transport message
    # exhausts its delivery budget (queue or push). The redrive surface's
    # default sweep filter matches on DEAD_LETTER_PROSE — producers and
    # that consumer must stay byte-identical, so both live here.
    DEAD_LETTER_PROSE = "delivery attempts exhausted"
    DEAD_LETTER = FAILED + " - " + DEAD_LETTER_PROSE

    @staticmethod
    def canonical(status: str) -> str:
        """Map a free-form status string onto its lifecycle bucket.

        The reference lets services write arbitrary status strings (e.g.
        "Awaiting service availability…", "completed - 3 animals found") but
        buckets them into the four sets by substring match
        (``CacheConnectorUpsert.cs:111-123``).
        """
        s = (status or "").lower()
        for canon in (TaskStatus.FAILED, TaskStatus.COMPLETED,
                      TaskStatus.EXPIRED, TaskStatus.RUNNING):
            if canon in s:
                return canon
        return TaskStatus.CREATED


def new_task_id() -> str:
    """GUID task ids, as in ``CacheConnectorUpsert.cs:99``."""
    return str(uuid.uuid4())


# Separator between a pipeline root TaskId and a stage name in stage
# sub-task ids ("{root}~{stage}", pipeline/spec.py). Lives here — beside
# the ':' result-stage separator it complements — because the store's
# external-TaskId validation must reject it: a client-supplied id
# carrying '~' could alias a running pipeline's stage sub-records (the
# coordinator routes terminal transitions by splitting on it).
SUB_TASK_SEP = "~"


def endpoint_path(endpoint: str) -> str:
    """Derived endpoint path, e.g. ``http://host/v1/landcover/classify`` →
    ``/v1/landcover/classify`` (``APITask.cs`` EndpointPath). Query strings
    and fragments never reach the set key — for bare paths as well as full
    URLs, so ``/v1/api?x=1`` and ``http://h/v1/api?x=1`` bucket together."""
    if not endpoint:
        return ""
    if "://" in endpoint:
        return urlparse(endpoint).path or "/"
    path = endpoint if endpoint.startswith("/") else "/" + endpoint
    return path.split("?", 1)[0].split("#", 1)[0] or "/"


@dataclass
class APITask:
    """A single unit of asynchronous work."""

    task_id: str = field(default_factory=new_task_id)
    timestamp: float = field(default_factory=time.time)
    status: str = TaskStatus.CREATED
    backend_status: str = TaskStatus.CREATED
    endpoint: str = ""
    body: bytes = b""
    content_type: str = "application/json"
    publish: bool = False  # PublishToGrid: enqueue onto the transport on upsert
    # Result-cache provenance (``rescache/``): the canonical request key the
    # gateway derived for this task, or "" for uncacheable/opted-out
    # requests. Rides the record (and the journal) so the store listener can
    # fill the cache on the terminal transition, the dispatcher can serve a
    # redelivery straight from the cache, and operators can see WHY a task
    # says "completed - served from cache".
    cache_key: str = ""
    # Admission state (admission/): the absolute wall-clock deadline
    # (unix seconds; 0.0 = none) the gateway anchored from the caller's
    # X-Deadline-Ms, and the priority class (0 interactive / 1 default /
    # 2 background). They ride the record, the wire, and the journal so
    # every hop — dispatcher pop, batcher cut, worker submit — can drop
    # already-dead work and shed lowest-priority-first.
    deadline_at: float = 0.0
    priority: int = 1
    # Tenant scope (tenancy/): the tenant id the gateway resolved from the
    # caller's subscription key — never the key itself. Rides the record,
    # the wire, and the journal so the broker can lane the message, the
    # dispatcher can charge placement cost, and the outcome feed can label
    # per-tenant series. "" = tenantless (layer off, or internal traffic).
    tenant: str = ""
    # Journal participation. False for records whose loss on restart is
    # acceptable — cache-hit tasks, whose terminal record was already in the
    # submit response: a JournaledTaskStore keeps them queryable in memory
    # but never appends them (or their results) to the journal, so a high
    # duplicate rate cannot turn "served from cache" into per-hit
    # payload-sized fsync I/O. Process-local like ``publish`` — never on the
    # wire or in the journal (a replayed record is durable by definition).
    durable: bool = True

    @property
    def endpoint_path(self) -> str:
        return endpoint_path(self.endpoint)

    @property
    def canonical_status(self) -> str:
        return TaskStatus.canonical(self.status)

    def to_dict(self) -> dict:
        """Wire shape returned to clients polling ``GET /task/{taskId}``
        (``CacheConnectorGet.cs:26-74`` returns the task JSON verbatim)."""
        d = {
            "TaskId": self.task_id,
            "Timestamp": self.timestamp,
            "Status": self.status,
            "BackendStatus": self.backend_status,
            "Endpoint": self.endpoint,
            "ContentType": self.content_type,
        }
        if self.cache_key:
            # Only when set: pre-cache records (and uncached tasks) keep the
            # exact reference wire shape.
            d["CacheKey"] = self.cache_key
        if self.deadline_at:
            # Same only-when-set rule: deadline-free traffic keeps the
            # reference wire shape byte for byte.
            d["DeadlineAt"] = self.deadline_at
        if self.priority != 1:
            d["Priority"] = self.priority
        if self.tenant:
            # Only when set — tenantless deployments keep the reference
            # wire shape byte for byte.
            d["Tenant"] = self.tenant
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "APITask":
        body = d.get("Body", b"")
        if isinstance(body, str):
            # Inverse of the client's surrogateescape decode — binary bodies
            # (JPEGs etc.) survive the JSON round trip.
            body = body.encode("utf-8", errors="surrogateescape")
        return cls(
            task_id=d.get("TaskId") or d.get("Uuid") or new_task_id(),
            timestamp=float(d.get("Timestamp") or time.time()),
            status=d.get("Status", TaskStatus.CREATED),
            backend_status=d.get("BackendStatus", TaskStatus.CREATED),
            endpoint=d.get("Endpoint", ""),
            body=body,
            content_type=d.get("ContentType", "application/json"),
            publish=bool(d.get("PublishToGrid", False)),
            cache_key=d.get("CacheKey", ""),
            deadline_at=float(d.get("DeadlineAt") or 0.0),
            priority=int(d.get("Priority") or 1),
            tenant=d.get("Tenant", ""),
        )

    def with_status(self, status: str, backend_status: str | None = None) -> "APITask":
        return replace(
            self,
            status=status,
            backend_status=backend_status if backend_status is not None else status,
            timestamp=time.time(),
        )
