"""Task reaper — failure detection for stuck tasks.

SURVEY.md §5 (failure detection): the reference's recovery story ends at the
broker — a message not yet acknowledged is redelivered
(``BackendQueueProcessor/host.json:7`` autoComplete:false), but a task whose
worker crashed AFTER adopting it (200 to the dispatcher, then the pod died
mid-inference) sits in ``running`` forever; nothing in the reference watches
for that. The journal keeps the task's original body durable
(``CacheConnectorUpsert.cs:158`` equivalent), so recovery is possible — this
component adds the missing detector.

``TaskReaper`` periodically scans the store's non-terminal tasks:

- a task in ``running`` longer than ``running_timeout`` is *orphaned*: the
  reaper republishes it (empty body → the store replays the original body,
  the transport redelivers to a healthy replica) under the same TaskId — the
  resume-by-TaskId behavior SURVEY.md §5 describes, now automatic;
- after ``max_requeues`` rescues the task is failed instead — a task that
  keeps killing workers must reach a terminal state, not cycle forever (the
  broker's max-delivery-count plays this role one layer down);
- tasks in ``created``/``awaiting`` are the transport's responsibility
  (lease expiry / redelivery) and are left alone.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from .store import InMemoryTaskStore
from .task import TaskStatus

log = logging.getLogger("ai4e_tpu.reaper")


class TaskReaper:
    def __init__(self, store: InMemoryTaskStore,
                 running_timeout: float | None = 600.0,
                 interval: float = 30.0,
                 max_requeues: int = 3,
                 terminal_retention: float | None = None,
                 owns=None,
                 metrics: MetricsRegistry | None = None):
        """``running_timeout`` None disables the stuck-task rescue;
        ``terminal_retention`` (seconds) evicts completed/failed history
        older than that — record, original body, results, offloaded blobs
        — bounding store memory and journal size over a long deployment
        (the Redis-expiry role; None keeps history forever).

        ``owns`` (optional, ``owns(task_id) -> bool``): shard-ownership
        filter for sharded deployments running one reaper per shard — a
        task whose hash slot was rebalanced away between the scan snapshot
        and the rescue belongs to the NEW owner's reaper and is skipped
        (docs/sharding.md). The store-level write fence (``NotOwnerError``)
        backstops this: even a reaper that skips the filter cannot land a
        stale-owner write. None (the default, and the facade-attached
        reaper in the single-process assembly) rescues the full keyspace
        it scans — actions route through the store it was given, which on
        the sharded facade means a fresh ring lookup per rescue."""
        self.store = store
        self.running_timeout = running_timeout
        self.interval = interval
        self.max_requeues = max_requeues
        self.terminal_retention = terminal_retention
        self.owns = owns
        self.metrics = metrics or DEFAULT_REGISTRY
        self._reaped = self.metrics.counter(
            "ai4e_reaper_actions_total", "Stuck-task rescues by outcome")
        self._requeues: dict[str, int] = {}
        self._task: asyncio.Task | None = None
        self._stop = asyncio.Event()

    async def start(self) -> None:
        self._stop.clear()
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await asyncio.wait_for(self._stop.wait(), timeout=self.interval)
                return
            except asyncio.TimeoutError:
                pass
            try:
                await self.sweep()
            except Exception:  # noqa: BLE001 — the watchdog must not die
                log.exception("reaper sweep failed")

    async def sweep(self) -> int:
        """One scan; returns the number of tasks acted on. The rescue pass
        costs O(running tasks) via the per-endpoint RUNNING status sets
        (the reference's ``{path}_running`` sorted sets); when
        ``terminal_retention`` is set, an eviction pass additionally scans
        (and prunes) the terminal sets — O(terminal history), which the
        eviction itself keeps bounded."""
        now = time.time()
        acted = 0
        if self.terminal_retention is not None:
            evict = getattr(self.store, "evict_terminal_older_than", None)
            if evict is not None:
                evicted = evict(self.terminal_retention)
                if evicted:
                    log.info("evicted %d terminal tasks older than %.0fs",
                             evicted, self.terminal_retention)
                    self._reaped.inc(evicted, outcome="evicted")
                    acted += evicted
        if self.running_timeout is None:
            return acted
        running = self._collect_running()
        running_ids = {t.task_id for t in running}
        # Release rescue budgets only on TERMINAL outcomes: a rescued task
        # waiting in CREATED (redelivery pending) must keep its count, or
        # max_requeues could never trip and a poison task would cycle forever.
        for tid in list(self._requeues):
            if tid in running_ids:
                continue
            try:
                status = self.store.get(tid).canonical_status
            except KeyError:
                del self._requeues[tid]
                continue
            if status in TaskStatus.TERMINAL:
                del self._requeues[tid]
        for task in running:
            age = now - task.timestamp
            if age < self.running_timeout:
                continue
            if not self._owned(task.task_id):
                # A rebalance moved this task's hash slot after the scan
                # snapshot: the NEW owner's sweep is responsible for it
                # now. Acting here would be the stale-owner rescue the
                # store fence refuses (NotOwnerError) — skip instead of
                # burning a routed rescue on a range mid-handoff.
                continue
            count = self._requeues.get(task.task_id, 0)
            # Conditional transitions: the task may have completed between
            # the snapshot and this action — a terminal task must never be
            # resurrected or overwritten (store.requeue_if/update_status_if
            # re-check atomically under the store lock).
            if count >= self.max_requeues:
                done = self.store.update_status_if(
                    task.task_id, TaskStatus.RUNNING,
                    f"failed - no progress after {count} rescues",
                    backend_status=TaskStatus.FAILED)
                if done is None:
                    continue
                log.warning("task %s stuck running after %d rescues; failed",
                            task.task_id, count)
                self._reaped.inc(outcome="failed")
            else:
                # Empty body → original-body replay; same endpoint; the
                # transport redelivers to any healthy replica.
                requeued = self.store.requeue_if(task.task_id,
                                                 TaskStatus.RUNNING)
                if requeued is None:
                    continue
                log.warning("task %s running %.0fs with no progress; "
                            "republished (rescue %d/%d)", task.task_id, age,
                            count + 1, self.max_requeues)
                self._requeues[task.task_id] = count + 1
                self._reaped.inc(outcome="requeued")
            acted += 1
        return acted

    def _collect_running(self) -> list:
        """Running-set snapshot. On a sharded store the scan is PER SHARD
        (each shard's status sets, not one whole-keyspace walk — the scan
        cost a shard pays is bounded by its own 1/N of the keyspace);
        unsharded stores scan exactly as before."""
        shards_fn = getattr(self.store, "shard_stores", None)
        sources = shards_fn() if shards_fn is not None else [self.store]
        running: list = []
        for source in sources:
            for path in source.endpoints():
                for task_id in source.set_members(path, TaskStatus.RUNNING):
                    try:
                        running.append(source.get(task_id))
                    except KeyError:
                        continue
        return running

    def _owned(self, task_id: str) -> bool:
        if self.owns is None:
            return True
        try:
            return bool(self.owns(task_id))
        except Exception:  # noqa: BLE001 — an ownership-probe fault must not kill the sweep
            log.exception("shard ownership probe failed for %s; skipping "
                          "rescue this sweep", task_id)
            return False
