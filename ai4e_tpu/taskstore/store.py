"""Task state store — the platform's core state machine.

Equivalent of the reference's CacheManager over Azure Redis
(``ProcessManager/CacheManager/CacheConnectorUpsert.cs:40-213`` /
``CacheConnectorGet.cs:26-74``), re-designed as a library with pluggable
backends instead of an Azure Function over a remote Redis:

- ``upsert`` creates a task (new GUID) or transitions an existing one, updating
  per-endpoint, per-status ordered sets scored by epoch seconds and removing the
  task from its prior status set (mirrors the Redis MULTI transaction at
  ``CacheConnectorUpsert.cs:125-170``). All of that happens under one lock here —
  the transactionality the reference got from Redis MULTI.
- the original request body is stored per task and replayed when a pipeline
  stage re-publishes the task with an empty body
  (``CacheConnectorUpsert.cs:144-176`` reads ``{taskId}_ORIG``).
- when a task is upserted with ``publish=True`` the store hands it to a
  publisher (the broker); a publish failure rolls the task to ``failed`` in the
  same breath (``CacheConnectorUpsert.cs:183-199``).
- ``JournaledTaskStore`` adds crash-durability via an append-only JSONL journal
  (replaces Redis persistence): on restart, replaying the journal rebuilds the
  exact store state so queued tasks survive worker crashes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Iterable

from dataclasses import replace

from .task import APITask, TaskStatus, new_task_id

Publisher = Callable[[APITask], None]


class TaskNotFound(KeyError):
    pass


class NotPrimaryError(RuntimeError):
    """A mutation reached a follower replica — only the primary accepts
    writes (the HTTP surface maps this to 503 so store clients fail over)."""


class StoreClosedError(RuntimeError):
    """A mutation reached a closed store (shutdown, or a shard primary the
    chaos harness SIGKILLed). RuntimeError subclass so pre-existing callers
    that caught the old bare RuntimeError keep working; the sharded facade
    keys its failover-promotion retry on this specific class."""


class NotOwnerError(RuntimeError):
    """A mutation reached a shard store for a TaskId the hash ring no longer
    assigns to it — the caller raced a rebalance handoff and is the stale
    owner (``taskstore/sharding.py``). Checked under the store lock, and the
    ring flip happens under the OLD owner's lock, so a stale write can never
    slip through the handoff window; the sharded facade re-routes via a
    fresh ring lookup, direct holders of the old shard fail loudly."""


class StaleEpochError(ValueError):
    """A demotion was attempted with an epoch no newer than the store's own
    — the caller is the stale side of the split, not this store (the HTTP
    surface maps this to 409)."""


class JournalDegradedError(RuntimeError):
    """The journal hit a disk fault (ENOSPC/EIO on append or fsync) and the
    store flipped to fenced read-only DEGRADED mode: reads keep serving,
    every mutation refuses with this error until ``recover()`` clears it —
    never an exception mid-mutation that leaves memory ahead of disk. The
    HTTP surfaces map it to a typed 503 with ``X-Shed-Reason:
    journal-degraded`` so breakers/orchestration see the node like a dark
    backend; the sharded facade treats it as a failover trigger
    (docs/durability.md#degraded-mode).

    ``rollback`` tells the raising append's caller whether the in-memory
    mutation must be unwound: True for write/flush failures (the record's
    bytes may be torn or absent on disk), False for fsync failures (the
    bytes ARE in the file — refusing the ack while keeping memory equal to
    the file is the honest state; the refused-but-durable record is the
    documented at-least-once residual)."""

    def __init__(self, message: str, rollback: bool = True):
        super().__init__(message)
        self.rollback = rollback


class StoreSideEffects:
    """Listener + publish side-effect plumbing shared by every store
    implementation (Python and native): transitions notify observers (e.g.
    the gateway's long-poll waiters) outside any lock, and a publish failure
    rolls the task to failed (``CacheConnectorUpsert.cs:183-199``)."""

    _publisher: Publisher | None
    _listeners: list

    def set_publisher(self, publisher: Publisher | None) -> None:
        self._publisher = publisher

    def add_listener(self, listener: Callable[["APITask"], None]) -> None:
        self._listeners.append(listener)

    def _notify(self, task: "APITask") -> None:
        for listener in self._listeners:
            try:
                listener(task)
            except Exception:  # noqa: BLE001 — observers must not break the store
                import logging
                logging.getLogger("ai4e_tpu.taskstore").exception(
                    "task listener failed for %s", task.task_id)

    def _publish_after(self, task: "APITask",
                      publisher: Publisher | None) -> None:
        if publisher is None:
            return
        try:
            publisher(task)
        except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the failure is recorded ON the task itself (failed - could not publish)
            self.update_status(
                task.task_id,
                f"failed - could not publish task: {exc}",
                backend_status=TaskStatus.FAILED,
            )

    def update_status(self, task_id, status, backend_status=None):
        raise NotImplementedError


class InMemoryTaskStore(StoreSideEffects):
    """Thread-safe in-process task store.

    Used directly by tests and single-process deployments; the HTTP task-store
    service (``taskstore.http``) wraps one of these, and multi-host deployments
    talk to that service the way reference services talk to the CacheConnector
    functions.
    """

    # True while applying already-accepted history verbatim (journal replay,
    # follower absorb, rebalance import): input validation AND the shard
    # write fence are both off — history must apply as-is.
    _absorbing = False
    # Closed stores refuse mutations (StoreClosedError); reads stay served.
    # The journaled subclass additionally closes its journal handle; the
    # base flag exists so journal-less shard primaries get SIGKILL
    # semantics too (chaos ``ShardGroup.mark_dead``).
    _closed = False

    def __init__(self, publisher: Publisher | None = None,
                 result_backend=None,
                 result_offload_threshold: int | None = None):
        self._lock = threading.RLock()
        self._tasks: dict[str, APITask] = {}
        # task_id -> (body, content_type): the replay record. Content type
        # rides along because republishes (pipeline handoff, saturation
        # requeue, reaper rescue) must redeliver the original payload with
        # its original type — a JPEG replayed as application/json would be
        # undecodable downstream.
        self._orig_bodies: dict[str, tuple[bytes, str]] = {}
        # key -> (payload, content_type); payload None means the bytes live
        # in the result backend (the blob-storage slot,
        # assign_storage_auth_to_aks.sh:9-17) — only the pointer is held here,
        # so completed-task memory doesn't grow with large batch outputs.
        self._results: dict[str, tuple[bytes | None, str]] = {}
        # task_id -> result keys owned by it ("{tid}" / "{tid}:{stage}"):
        # eviction must be O(victim's results), not O(all results) — the
        # 40-min soak wedged the store for minutes when each of ~6k
        # victims scanned ~190k result keys under the lock
        # (bench_results/r5-cpu/).
        self._result_keys: dict[str, set[str]] = {}
        self._result_backend = result_backend
        self._result_offload_threshold = result_offload_threshold
        # (endpoint_path, canonical_status) -> {task_id: score}; insertion
        # ordered + scored like the reference's Redis sorted sets.
        self._sets: dict[tuple[str, str], dict[str, float]] = {}
        self._publisher = publisher
        # Shard ownership fence (``taskstore/sharding.py``): when set, every
        # task/result mutation verifies — under this store's lock — that the
        # hash ring still assigns the TaskId here; a stale owner raises
        # NotOwnerError instead of applying an orphan write. None (the
        # default, every unsharded deployment) is a no-op.
        self._write_fence: Callable[[str], bool] | None = None
        # Change listeners (e.g. the gateway's long-poll waiters). Called
        # outside the lock, after every state transition, possibly from any
        # thread — listeners must be cheap and thread-safe
        # (StoreSideEffects._notify).
        self._listeners: list[Callable[[APITask], None]] = []
        # Hop-ledger timelines (observability/ledger.py): task_id ->
        # [event dicts], appended by every hop when the observability
        # layer is on. Observability state, NOT durable truth — never
        # journaled, dropped with the record at eviction; a restart
        # loses timelines, never tasks (docs/observability.md).
        self._ledgers: dict[str, list[dict]] = {}

    # -- core state machine ------------------------------------------------

    def upsert(self, task: APITask) -> APITask:
        """Create or transition a task; returns the stored record.

        Semantics of ``CacheConnectorUpsert.TaskRun``:
        - no existing record → create (fresh GUID unless one was supplied);
          non-empty body is remembered as the original body for pipeline replay;
        - existing record → status transition; an empty body on a *publishing*
          upsert is a subsequent pipeline call and replays the original body;
        - status-set bookkeeping: remove from prior set, add to new set scored
          by now;
        - ``publish=True`` → hand to the broker; on broker failure the task is
          marked failed instead of raising to the caller.

        Client-supplied TaskIds must not contain ``:`` — it is the result
        namespace's stage separator (``{taskId}:{stage}`` keys), and an id
        carrying one would alias another task's result keys (eviction
        could then leak this task's results or destroy a neighbor's).
        The guard runs on EXTERNAL write paths only (``_validates_task_ids``):
        journal replay and follower absorb apply history as-is — a legacy
        pre-guard journal must never crash-loop ``__init__._replay`` or
        wedge a follower's absorb/retry loop at a fixed offset (ADVICE r5).
        """
        with self._lock:
            # Validation decision UNDER the lock: ``_absorbing`` flips under
            # it (rebalance import), and a pre-lock read could skip the
            # guard for an unrelated external upsert racing an import.
            if ":" in task.task_id and self._validates_task_ids():
                raise ValueError(
                    f"TaskId must not contain ':' (reserved as the result "
                    f"stage separator): {task.task_id!r}")
            # NOTE: '~' (task.SUB_TASK_SEP, pipeline stage sub-tasks) is
            # deliberately NOT rejected here — the coordinator mints
            # "{root}~{stage}" ids through this very path. The HTTP
            # surface refuses CREATES of unknown '~' ids instead
            # (taskstore/http.py), which is where a forged alias could
            # enter; in-process callers are platform code.
            task = self._apply_upsert(task)
            publisher = self._publisher if task.publish else None

        self._notify(task)
        self._publish_after(task, publisher)
        return task

    def _validates_task_ids(self) -> bool:
        """Whether upsert enforces input validation — True on every external
        write path; off while absorbing history (rebalance import here; the
        journaled subclass additionally turns it off while replaying —
        records that were already accepted once must apply verbatim, or a
        restart/follower can never catch up)."""
        return not self._absorbing

    def set_write_fence(self, fence: Callable[[str], bool] | None) -> None:
        """Install (or clear) the shard ownership fence — ``fence(task_id)``
        must answer True iff this store currently owns the id. Called under
        the store lock on every mutation, so it must be cheap and must not
        take other locks (the ring lookup is arithmetic + a list read)."""
        self._write_fence = fence

    def _check_owner(self, task_id: str) -> None:
        """Shard-fence gate for task/result mutations. Skipped while
        absorbing (history applies verbatim — the rebalance import IS the
        new owner receiving the range) and for empty ids (the id is minted
        below, by a store that trivially owns a fresh GUID). Eviction is
        deliberately NOT fenced: it is garbage collection — it can neither
        resurrect nor clobber a task — and the migration's own post-flip
        cleanup of the moved range runs as the (by then) non-owner."""
        fence = self._write_fence
        if fence is None or self._absorbing or not task_id:
            return
        if not fence(task_id):
            raise NotOwnerError(
                f"task {task_id} is no longer owned by this shard "
                "(rebalance moved its hash slot); route via the ring")

    def _apply_upsert(self, task: APITask) -> APITask:
        """State mutation for upsert. Caller holds ``self._lock``; subclasses
        extend this to journal atomically with the mutation."""
        self._check_open()
        self._check_owner(task.task_id)
        prev = self._tasks.get(task.task_id)
        if prev is None:
            if not task.task_id:
                task.task_id = new_task_id()
            if task.body:
                self._orig_bodies[task.task_id] = (task.body, task.content_type)
        else:
            if not task.cache_key:
                # Cache provenance survives pipeline handoffs and requeues:
                # the terminal result of the LAST stage is what the original
                # request's cache key should resolve to (rescache/wiring.py).
                task.cache_key = prev.cache_key
            if not task.deadline_at:
                # Admission state survives handoffs/requeues the same way:
                # a pipeline's second stage runs under the ORIGINAL
                # request's deadline (the caller's budget covers the whole
                # composite), and a requeue must not shed its class label.
                task.deadline_at = prev.deadline_at
            if task.priority == 1 and prev.priority != 1:
                task.priority = prev.priority
            if not prev.durable:
                # Memory-only stays memory-only: an external full upsert
                # (facade records default durable=True) must not promote a
                # cache-hit record into the journal — its create was never
                # journaled, so replay would drop the slim transitions
                # silently and compaction would write the very payload-sized
                # records durable=False exists to prevent.
                task.durable = False
            if not task.body and task.publish:
                # Subsequent pipeline call: replay the original body + its
                # content type (CacheConnectorUpsert.cs:144-176).
                task.body, task.content_type = self._orig_bodies.get(
                    task.task_id, (b"", task.content_type))
            elif task.body and task.publish:
                # Pipeline handoff with a fresh payload (e.g. detector crops
                # for the classifier): that payload is now the task's replay
                # body — a later empty-body requeue of the new stage must get
                # the stage's own input, not stage 1's.
                self._orig_bodies[task.task_id] = (task.body, task.content_type)
            self._remove_from_set(prev)
        if not (self._absorbing and task.timestamp):
            # Live mutations stamp "now"; absorbed history (follower
            # absorb, rebalance import) keeps the record's own timestamp so
            # set scores and the reaper's age clock survive the handoff —
            # and the journaled subclass's append then serializes the TRUE
            # timestamp, so a restart of the absorbing store replays it.
            task.timestamp = time.time()
        self._tasks[task.task_id] = task
        self._add_to_set(task)
        return task

    def update_status(
        self, task_id: str, status: str, backend_status: str | None = None
    ) -> APITask:
        """Atomic status transition by id — no read-modify-write race (the
        reference's ``_UpdateTaskStatus`` GET-then-POST at
        ``distributed_api_task.py:29-56`` is racy; SURVEY.md §5 flags it)."""
        with self._lock:
            task = self._apply_update(task_id, status, backend_status)
        self._notify(task)
        return task

    def _apply_update(
        self, task_id: str, status: str, backend_status: str | None
    ) -> APITask:
        """State mutation for update. Caller holds ``self._lock``."""
        self._check_open()
        self._check_owner(task_id)
        prev = self._tasks.get(task_id)
        if prev is None:
            raise TaskNotFound(task_id)
        task = prev.with_status(status, backend_status)
        task.publish = False
        self._remove_from_set(prev)
        self._tasks[task_id] = task
        self._add_to_set(task)
        return task

    # -- atomic conditional transitions (the reaper's rescue path: a sweep
    # decision taken from a snapshot must not clobber a task that reached a
    # terminal state in the meantime) ---------------------------------------

    def requeue_if(self, task_id: str, expected_status: str) -> APITask | None:
        """Republish the task (empty body → original replay) iff its
        canonical status is still ``expected_status``; None otherwise."""
        with self._lock:
            current = self._tasks.get(task_id)
            if current is None or current.canonical_status != expected_status:
                return None
            task = self._apply_upsert(APITask(
                task_id=task_id, endpoint=current.endpoint, body=b"",
                status=TaskStatus.CREATED, backend_status=TaskStatus.CREATED,
                content_type=current.content_type, publish=True))
            publisher = self._publisher if task.publish else None
        self._notify(task)
        self._publish_after(task, publisher)
        return task

    def update_status_if(self, task_id: str, expected_status: str,
                         status: str,
                         backend_status: str | None = None) -> APITask | None:
        """Status transition iff the canonical status is still
        ``expected_status``; None otherwise."""
        with self._lock:
            current = self._tasks.get(task_id)
            if current is None or current.canonical_status != expected_status:
                return None
            task = self._apply_update(task_id, status, backend_status)
        self._notify(task)
        return task

    def get(self, task_id: str) -> APITask:
        with self._lock:
            task = self._tasks.get(task_id)
            if task is None:
                raise TaskNotFound(task_id)
            return task

    # -- hop ledger (observability/ledger.py) -------------------------------

    def append_ledger(self, task_id: str, events: list[dict]) -> int:
        """Append hop-ledger events to a known task's timeline; returns
        the events actually kept (the per-task cap —
        ``observability.ledger.MAX_EVENTS``, the same bound the worker's
        HopLedger buffers to — drops overflow with a single
        ``truncated`` marker). Raises TaskNotFound for unknown ids and
        refuses on closed/follower/non-owner stores like every other
        mutation — callers (the observability hub, the HTTP surface)
        treat all of those as droppable: the ledger is fail-open
        telemetry, not task state."""
        from ..observability.ledger import (MAX_EVENTS, TRUNCATED,
                                            ledger_event)
        check_writable = getattr(self, "_check_writable", None)
        with self._lock:
            self._check_open()
            if check_writable is not None:
                check_writable()
            self._check_owner(task_id)
            if task_id not in self._tasks:
                raise TaskNotFound(task_id)
            timeline = self._ledgers.setdefault(task_id, [])
            kept = 0
            for ev in events:
                if len(timeline) >= MAX_EVENTS:
                    if (not timeline
                            or timeline[-1].get("e") != TRUNCATED):
                        timeline.append(ledger_event(TRUNCATED, "store"))
                    break
                timeline.append(ev)
                kept += 1
            return kept

    def get_ledger(self, task_id: str) -> list[dict]:
        """The task's timeline (empty for unknown tasks or when the
        observability layer never stamped — reads never raise: the
        ledger query is a debugging surface)."""
        with self._lock:
            return list(self._ledgers.get(task_id, ()))

    def dump_ledgers(self, limit: int = 5000) -> dict[str, list[dict]]:
        """Every resident timeline (bounded) — the rig driver's
        pre-teardown collection surface (``GET /v1/rig/ledgers``): hop
        ledgers are memory-only observability state, so the timeline
        exporter must read them out before the process dies with them
        (docs/observability.md). Newest-stamped last; reads never
        raise."""
        with self._lock:
            items = list(self._ledgers.items())
        if limit >= 0:
            items = items[-limit:] if limit else []
        return {tid: list(evs) for tid, evs in items}

    # -- retention (terminal-history eviction) ------------------------------

    def evict_terminal_older_than(self, age_s: float) -> int:
        """Remove terminal (completed/failed) tasks older than ``age_s``
        seconds — record, status-set entry, original body, results, and any
        offloaded blobs. Without this a long-running store's memory and
        journal grow with every task ever finished (the reference leans on
        Redis eviction/expiry for the same role). Returns tasks evicted.
        Cost is O(terminal history), which this very mechanism keeps
        bounded at ~(completion rate × retention). Set scores are NOT
        assumed monotone — journal compaction rewrites tasks in creation
        order, so a full scan is the only correct victim collection."""
        cutoff = time.time() - age_s
        blob_keys: list[str] = []
        evicted = 0
        try:
            with self._lock:
                victims = []
                for (path, status), members in self._sets.items():
                    if status not in TaskStatus.TERMINAL:
                        continue
                    victims.extend(task_id
                                   for task_id, score in members.items()
                                   if score < cutoff)
                for task_id in victims:
                    blob_keys.extend(self._apply_evict(task_id))
                    evicted += 1
        finally:
            # Backend I/O OUTSIDE the lock (a GCS/PD delete is a network
            # round trip; thousands of victims on a first sweep must not
            # stall every store operation). Crash-ordering: the journaled
            # subclass appends the Evict record inside _apply_evict, i.e.
            # BEFORE these deletes — a crash in between leaks blobs
            # harmlessly instead of replaying a completed task whose
            # offloaded result is gone. Runs in a finally: on a mid-batch
            # journal-degraded abort, earlier victims are already evicted
            # AND journaled, so no record references their blobs — skipping
            # the deletes would orphan them on the mount forever (review
            # finding; the aborted victim itself rolled back and kept its
            # pointers, so its keys never reach blob_keys).
            for key in blob_keys:
                self._delete_blob(key)
        return evicted

    def _apply_evict(self, task_id: str) -> list[str]:
        """Forget one task entirely; returns offloaded-result keys whose
        blobs the CALLER must delete (outside the lock). Caller holds
        ``self._lock``; the journaled subclass extends this."""
        task = self._tasks.pop(task_id, None)
        if task is None:
            return []
        self._remove_from_set(task)
        self._orig_bodies.pop(task_id, None)
        self._ledgers.pop(task_id, None)
        blob_keys = []
        # O(this task's results) via the key index — NEVER a scan of all
        # results (each victim of a bulk eviction would pay O(history)).
        for key in self._result_keys.pop(task_id, ()):
            found = self._results.pop(key, None)
            if found is not None and found[0] is None:
                blob_keys.append(key)
        return blob_keys

    def get_original_body(self, task_id: str) -> bytes:
        with self._lock:
            return self._orig_bodies.get(task_id, (b"", ""))[0]

    # -- results (the reference delegates results to external blob storage;
    # here they're first-class, keyed like {taskId}_RESULT) -----------------

    def set_result(self, task_id: str, result: bytes,
                   content_type: str = "application/json",
                   stage: str | None = None) -> None:
        """Store a task's result payload. ``stage`` stores an intermediate
        pipeline-stage result (keyed ``{taskId}:{stage}``) without touching
        the final result — so each stage of a composite API leaves its output
        retrievable under the shared TaskId, analogous to the reference
        keeping ``{taskId}_ORIG`` alongside the task (``CacheConnectorUpsert.cs:158``)."""
        key = task_id if stage is None else f"{task_id}:{stage}"
        owner = self._tasks.get(task_id)
        offload = (self._result_backend is not None
                   and self._result_offload_threshold is not None
                   and len(result) >= self._result_offload_threshold
                   # Non-durable records (cache hits) are memory-only: their
                   # results stay inline — per-hit blob writes would put
                   # payload-sized I/O back on the exact path the cache
                   # exists to avoid, and a restart would orphan the blobs
                   # on the mount (no journaled record references them, so
                   # no eviction ever deletes them).
                   and (owner is None or owner.durable))
        if offload:
            # Write the blob BEFORE taking the lock (it may be slow storage)
            # and before the pointer becomes visible — a reader that sees the
            # pointer must always find the blob.
            self._result_backend.put(key, result, content_type)
        try:
            with self._lock:
                if task_id not in self._tasks:
                    raise TaskNotFound(task_id)
                self._apply_set_result(key, None if offload else result,
                                       content_type)
        except Exception:
            # Reap the just-written blob UNLESS an offloaded pointer for
            # this key is visible in memory — the one invariant that
            # matters: visible pointer ⇒ its blob must exist. No pointer
            # (unknown/reaped task, closed store, degraded rollback of a
            # fresh result) ⇒ nothing references the blob and it would
            # leak on the mount forever. A visible pointer survives here
            # two ways: the key already held one (put() overwrote that
            # blob in place — deleting would dangle it; the residual is
            # the blob serving the refused write's bytes,
            # docs/durability.md#degraded-mode), or a rollback=False
            # fsync failure applied the mutation to match the file.
            with self._lock:
                now = self._results.get(key)
            if offload and not (now is not None and now[0] is None):
                self._delete_blob(key)
            raise

    def _apply_set_result(self, key: str, result: bytes | None,
                          content_type: str) -> None:
        """Result mutation (``result is None`` = offloaded pointer). Caller
        holds ``self._lock``; the journaled subclass extends this."""
        self._check_open()
        self._check_owner(key.split(":", 1)[0])
        self._set_result_in_memory(key, result, content_type)

    def _set_result_in_memory(self, key: str, result: bytes | None,
                              content_type: str) -> None:
        """The unchecked memory half of a result write. Split out so the
        journaled subclass can apply it AFTER a failed-but-durable append
        (rollback=False), when the open/degraded re-check would refuse a
        mutation whose record is already in the file."""
        prev = self._results.get(key)
        self._results[key] = (result, content_type)
        self._result_keys.setdefault(key.split(":", 1)[0], set()).add(key)
        if (prev is not None and prev[0] is None and result is not None):
            # An inline value superseded an offloaded pointer — the stale
            # blob is unreachable now; delete it. (Pointer→pointer rewrites
            # overwrite the same blob file in put().)
            self._delete_blob(key)

    def _delete_blob(self, key: str) -> None:
        if self._result_backend is None:
            return
        try:
            self._result_backend.delete(key)
        except Exception:  # noqa: BLE001 — cleanup must not mask the result path
            import logging
            logging.getLogger("ai4e_tpu.taskstore").exception(
                "could not delete result blob %s", key)

    def get_result(self, task_id: str,
                   stage: str | None = None) -> tuple[bytes, str] | None:
        key = task_id if stage is None else f"{task_id}:{stage}"
        with self._lock:
            found = self._results.get(key)
        if found is None:
            return None
        body, content_type = found
        if body is None:  # offloaded — fetch from the backend outside the lock
            if self._result_backend is None:
                return None  # unreachable after replay's fail-fast; be safe
            fetched = self._result_backend.get(key)
            if fetched is None:
                return None
            return fetched
        return body, content_type

    def set_result_ref(self, task_id: str,
                       content_type: str = "application/json",
                       stage: str | None = None) -> None:
        """Register a result the caller ALREADY wrote to the shared backend
        under the canonical key — the direct-to-storage worker path (the
        reference gives its containers blob-storage access so outputs never
        transit the control plane, ``assign_storage_auth_to_aks.sh:9-17``).
        The blob's existence is verified BEFORE the pointer becomes visible:
        a reader that sees the pointer must always find the blob."""
        if self._result_backend is None:
            raise RuntimeError(
                "no result backend configured (set result_dir) — cannot "
                "register a direct-to-storage result")
        key = task_id if stage is None else f"{task_id}:{stage}"
        found = self._result_backend.open(key)
        if found is None:
            raise FileNotFoundError(
                f"result blob {key!r} not present in the backend — write "
                "it before registering the pointer")
        found[0].close()
        with self._lock:
            if task_id not in self._tasks:
                raise TaskNotFound(task_id)
            self._apply_set_result(key, None, content_type)

    def open_result(self, task_id: str, stage: str | None = None):
        """Streaming accessor: ``(file_like, content_type, size)`` or None.
        Offloaded results stream straight from the backend (a multi-MB
        batch output never buffers whole in store/server memory); inline
        results adapt through BytesIO so callers have ONE read path."""
        key = task_id if stage is None else f"{task_id}:{stage}"
        with self._lock:
            found = self._results.get(key)
        if found is None:
            return None
        body, content_type = found
        if body is None:
            if self._result_backend is None:
                return None
            return self._result_backend.open(key)
        import io
        return io.BytesIO(body), content_type, len(body)

    # -- status-set queries (queue-depth metrics, QueueLogger.cs:21-47) ----

    def set_len(self, endpoint_path: str, status: str) -> int:
        with self._lock:
            return len(self._sets.get((endpoint_path, status), {}))

    def set_members(self, endpoint_path: str, status: str) -> list[str]:
        with self._lock:
            members = self._sets.get((endpoint_path, status), {})
            return sorted(members, key=members.__getitem__)

    def endpoints(self) -> list[str]:
        with self._lock:
            return sorted({path for path, _ in self._sets})

    def depths(self) -> dict[str, dict[str, int]]:
        """Per-endpoint per-status depths — the autoscaling signal
        (``TaskQueueLogger.cs:19-27`` logs ``_created`` depth every 30 s)."""
        with self._lock:
            out: dict[str, dict[str, int]] = {}
            for (path, status), members in self._sets.items():
                out.setdefault(path, {s: 0 for s in TaskStatus.ALL})[status] = len(members)
            return out

    # -- internals ---------------------------------------------------------

    def _add_to_set(self, task: APITask) -> None:
        key = (task.endpoint_path, task.canonical_status)
        self._sets.setdefault(key, {})[task.task_id] = task.timestamp

    def _remove_from_set(self, task: APITask) -> None:
        key = (task.endpoint_path, task.canonical_status)
        members = self._sets.get(key)
        if members is not None:
            members.pop(task.task_id, None)

    def snapshot(self) -> Iterable[APITask]:
        with self._lock:
            return list(self._tasks.values())

    def unfinished_tasks(self) -> list[APITask]:
        """Tasks in a non-terminal state (created/awaiting/running) — what a
        restarted platform must re-dispatch. Bodies are restored from the
        original-body record so redelivery carries the real payload."""
        with self._lock:
            out = []
            for task in self._tasks.values():
                if task.canonical_status in TaskStatus.TERMINAL:
                    continue
                if not task.body:
                    body, ctype = self._orig_bodies.get(
                        task.task_id, (b"", task.content_type))
                    task = replace(task, body=body, content_type=ctype)
                out.append(task)
            return out

    # -- record shapes shared by the journal and the rebalance wire --------
    # (defined here, not on the journaled subclass: the migration between
    # shards uses the same full-record format whether or not the shard
    # stores are journaled — docs/sharding.md)

    def _full_record(self, task: APITask) -> dict:
        """The journal's full (non-slim) record shape — one source of truth
        for appends, compaction rewrites, and rebalance exports."""
        rec = task.to_dict()
        rec["BodyHex"] = task.body.hex()
        orig = self._orig_bodies.get(task.task_id)
        if orig is not None:
            rec["OrigHex"] = orig[0].hex()
            rec["OrigContentType"] = orig[1]
        return rec

    def _result_record(self, key: str, body: bytes | None,
                       content_type: str) -> dict:
        rec = {"Result": True, "Key": key, "ContentType": content_type}
        if body is None:
            # Offloaded: the payload is durable in the result backend; the
            # journal carries only the pointer (no hex-doubling of large
            # blobs — offload exists precisely to keep them out of memory
            # and out of the journal).
            rec["Offloaded"] = True
        else:
            rec["ResultHex"] = body.hex()
        return rec

    # -- rebalance handoff (``taskstore/sharding.py`` move_slot) -----------

    def export_task_records(self, task_ids) -> list[dict]:
        """Full journal-shaped records (task + original body + its results)
        for the given ids — the rebalance wire format the new owner
        ``import_task_records``s. Task records come first so import applies
        them before their results, exactly like compaction/replay ordering.
        Non-durable records (memory-only cache hits) are skipped: their
        loss on a handoff has the same contract as their loss on a restart
        (the TaskId 404s; the terminal answer was already served)."""
        with self._lock:
            recs: list[dict] = []
            wanted = []
            for tid in task_ids:
                task = self._tasks.get(tid)
                if task is None or not task.durable:
                    continue
                wanted.append(tid)
                recs.append(self._full_record(task))
            for tid in wanted:
                for key in self._result_keys.get(tid, ()):
                    found = self._results.get(key)
                    if found is not None:
                        recs.append(self._result_record(key, found[0],
                                                        found[1]))
            return recs

    def import_task_records(self, recs: list[dict]) -> int:
        """Absorb migrated history from another shard. Applied verbatim like
        journal replay — no id validation, no publish, no listener
        notification (every transition already notified on the exporting
        shard; re-notifying here would be the duplicate-completion the
        chaos invariants reject) — and, on a journaled store, appended to
        the local journal so the imported range survives a restart of THIS
        shard. Idempotent: re-importing a record overwrites with identical
        state (the delta pass of ``move_slot`` relies on this)."""
        applied = 0
        with self._lock:
            self._check_open()
            prev_absorbing = self._absorbing
            self._absorbing = True
            # Defer auto-compaction past the import (journaled stores): the
            # rebalance delta pass runs this while holding the SOURCE
            # shard's lock, and an O(all tasks) compaction rewrite here
            # would stall the source's entire keyspace for its duration.
            # The next ordinary append — outside any foreign lock — picks
            # the deferred compaction up.
            prev_compact_at = getattr(self, "_next_compact_at", None)
            if prev_compact_at is not None:
                self._next_compact_at = float("inf")
            try:
                for rec in recs:
                    if self._apply_import(rec):
                        applied += 1
            finally:
                self._absorbing = prev_absorbing
                if prev_compact_at is not None:
                    self._next_compact_at = prev_compact_at
        return applied

    def _apply_import(self, rec: dict) -> bool:
        """Apply ONE migrated record. Caller holds ``self._lock`` with
        ``_absorbing`` set. Epoch markers are skipped — a fencing epoch is
        the exporting shard's lineage, never the importer's."""
        if "Epoch" in rec or rec.get("Evict") or rec.get("Slim"):
            return False  # migration exports full state only
        if rec.get("Result"):
            body = (None if rec.get("Offloaded")
                    else bytes.fromhex(rec.get("ResultHex", "")))
            self._apply_set_result(rec["Key"], body,
                                   rec.get("ContentType",
                                           "application/json"))
            return True
        task = APITask.from_dict(rec)
        task.body = bytes.fromhex(rec.get("BodyHex", ""))
        # Never re-publish: the task's broker message (if any) already
        # exists on the transport; the ring routes its status writes here.
        task.publish = False
        self._apply_upsert(task)  # _absorbing → timestamp preserved
        orig = rec.get("OrigHex")
        if orig:
            self._orig_bodies[task.task_id] = (
                bytes.fromhex(orig),
                rec.get("OrigContentType", "application/json"))
        return True

    # True while forget_tasks drops a migrated range: the journaled
    # subclass's Evict records then carry KeepBlobs, so neither this drop
    # NOR a later replay of it deletes result blobs the importing shard's
    # pointers now own (shards share one result backend). Only ever
    # flipped under ``self._lock``.
    _forgetting = False

    def forget_tasks(self, task_ids) -> int:
        """Drop the given tasks from this store entirely — the old owner's
        post-flip cleanup after a rebalance export. Unlike eviction, the
        offloaded result blobs are NOT deleted (see ``_forgetting``)."""
        with self._lock:
            dropped = 0
            self._forgetting = True
            try:
                for tid in list(task_ids):
                    if tid in self._tasks:
                        self._apply_evict(tid)  # blob keys deliberately unused
                        dropped += 1
            finally:
                self._forgetting = False
            return dropped

    def _check_open(self) -> None:
        # Refuse BEFORE mutating (the journaled subclass shares this flag
        # and additionally guards its journal handle).
        if self._closed:
            raise StoreClosedError("task store is closed")

    def close(self) -> None:
        self._closed = True


class JournaledTaskStore(InMemoryTaskStore):
    """InMemoryTaskStore + append-only JSONL journal for crash recovery.

    Plays the durability role Redis plays in the reference: a restarted store
    replays the journal and resumes with identical task state, so a crashed
    worker's tasks are still present for redelivery (SURVEY.md §5
    checkpoint/resume).
    """

    # Class-level default so _validates_task_ids is safe during __init__
    # replay on this class too (FollowerTaskStore overrides per instance
    # while absorbing).
    _absorbing = False

    def __init__(self, journal_path: str, publisher: Publisher | None = None,
                 compact_every: int = 5000, result_backend=None,
                 result_offload_threshold: int | None = None,
                 fsync: str | None = None, metrics=None):
        super().__init__(publisher, result_backend=result_backend,
                         result_offload_threshold=result_offload_threshold)
        from . import journal as journal_format
        from ..metrics import DEFAULT_REGISTRY
        self._journal_format = journal_format
        self._journal_path = journal_path
        self._journal = None  # gate journaling off during replay
        self._closed = False
        # Fsync policy (docs/durability.md): never (default — today's
        # write+flush behavior), always (fsync per append), group:<ms>
        # (batched group commit). None resolves AI4E_TASKSTORE_FSYNC;
        # a malformed value fails HERE, at construction.
        self._fsync_kind, self._fsync_group_s = (
            journal_format.parse_fsync_policy(fsync))
        self._fsync_last = 0.0
        self._fsync_timer = None        # pending group-commit timer
        self._fsync_dirty = False       # bytes flushed but not yet fsynced
        # Disk-fault degraded mode: set by _enter_degraded on EIO/ENOSPC;
        # every mutation refuses with JournalDegradedError until recover().
        self.degraded = False
        self.degraded_reason: str | None = None
        # Hash-chain head over this store's own journal file (journal.py):
        # two stores holding the same bytes hold the same head, so
        # divergence is a string comparison (topology/role endpoints).
        self.chain_head = journal_format.GENESIS
        # Blessed default-resolution idiom (AIL002): the assembly plumbs
        # its registry; standalone construction falls back in one visible
        # expression.
        metrics = metrics or DEFAULT_REGISTRY
        self._m_fsyncs = metrics.counter(
            "ai4e_journal_fsyncs_total",
            "Journal fsync calls, by fsync policy")
        self._m_appended = metrics.counter(
            "ai4e_journal_appended_bytes_total",
            "Bytes appended to task-store journals")
        self._m_salvages = metrics.counter(
            "ai4e_journal_salvages_total",
            "Torn journal tails truncated at open, by reason")
        self._m_verify_fail = metrics.counter(
            "ai4e_journal_verify_failures_total",
            "Journal records that failed checksum/chain verification")
        self._m_degraded = metrics.gauge(
            "ai4e_journal_degraded",
            "1 while the store refuses mutations after a journal disk "
            "fault (read-only degraded mode)")
        self._m_degraded_total = metrics.counter(
            "ai4e_journal_degraded_total",
            "Times a journal disk fault flipped the store to degraded "
            "mode, by errno name")
        self._m_compactions = metrics.counter(
            "ai4e_journal_compactions_total",
            "Journal compaction rewrites")
        self._m_append_s = metrics.histogram(
            "ai4e_journal_append_seconds",
            "Journal append wall time (write+flush+policy fsync)")
        # Instance-level stats for bench's `journal` result block — the
        # registry aggregates across stores; these stay per store.
        self._stat_bytes = 0
        self._stat_fsyncs = 0
        self._stat_compactions = 0
        self._stat_salvages = 0
        self._append_times: list[float] = []
        # Auto-compaction: status transitions append forever, so a
        # long-running store's journal (and restart replay time) would grow
        # without bound. Once ``compact_every`` records accumulate beyond
        # the live-task count, the journal is rewritten as one record per
        # task under the lock (atomic tmp+rename) — Redis AOF-rewrite's
        # role, sized so compaction cost amortizes to ~zero per write.
        self._compact_every = compact_every
        self._records = 0
        self._next_compact_at = compact_every
        # Bumped on every compaction rewrite: replication followers track
        # (generation, byte offset) into the journal file, and a rewrite
        # invalidates their offset — a generation mismatch tells them to
        # resync from offset 0 (the compacted journal IS the full state).
        self.journal_generation = 0
        # Split-brain fencing epoch (VERDICT r4 #3) — the monotonic counter
        # of the primary lineage this store's state belongs to. Minted +1 at
        # every promotion and journaled, so it survives restarts and a
        # re-promotion always exceeds every epoch this node has ever seen.
        # The single-writer property the reference bought from managed Redis
        # (RedisConnection.cs:12-38) made explicit: a primary that learns of
        # a higher epoch (client header, demote call, journal-stream probe)
        # self-demotes and refuses writes.
        self.epoch = 0
        self.replayed_task_ids: set[str] = set()
        if os.path.exists(journal_path):
            # Salvage BEFORE replay and before the append handle opens: a
            # torn final record (mid-write crash) is truncated to the last
            # complete verified record, so (a) replay can never crash-loop
            # on a torn tail and (b) the "a"-mode handle below can never
            # concatenate the next record onto torn bytes — the bug a
            # skip-only replay fix would leave behind. A corrupt INTERIOR
            # record raises loudly here with its offset instead
            # (journal.salvage; docs/durability.md).
            report = journal_format.salvage(journal_path)
            if report is not None:
                import logging
                logging.getLogger("ai4e_tpu.taskstore").warning(
                    "journal %s: salvaged torn tail — dropped %d bytes at "
                    "offset %d (%s); %d records kept, chain head %s "
                    "(report: %s.salvage.json)", journal_path,
                    report.dropped_bytes, report.truncated_at,
                    report.reason, report.records_kept, report.chain_head,
                    journal_path)
                self._m_salvages.inc(reason=report.reason)
                self._stat_salvages += 1
            self._replay()
            self.replayed_task_ids = set(self._tasks)
            # Same heuristic as runtime auto-compaction: only rewrite when
            # the journal is meaningfully bloated — a strictly-greater test
            # would rewrite (and fsync) the whole journal on nearly every
            # restart for a negligible win.
            if self._records > 2 * max(self._live_records(), 1):
                self._compact_locked()
        if self._journal is None:
            self._journal = open(journal_path, "a",  # noqa: SIM115
                                 encoding="utf-8")

    def _replay(self) -> None:
        # Salvage already verified the file end to end; the replay pass
        # re-verifies as it applies (cheap — CRC of control-plane-sized
        # records) so the chain head comes out of one code path.
        chain = self._journal_format.GENESIS
        with open(self._journal_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec, chain, _legacy = self._journal_format.verify_line(
                    line, chain)
                self._records += 1
                self._apply_replay_record(rec)
        self.chain_head = chain

    def _apply_replay_record(self, rec: dict) -> "APITask | None":
        """Apply ONE journal record to in-memory state — the replay step,
        also the unit a replication follower applies per streamed line
        (``replication.py``). Journaling is gated off in both cases
        (``self._journal is None``), so applying never re-appends.

        Returns the transitioned task for Slim records (the follower must
        ``_notify`` its own long-poll waiters of replicated transitions —
        the full-upsert branch already notifies via ``upsert``); None
        otherwise."""
        if "Epoch" in rec:
            # Fencing-epoch marker (promotion mint or demotion fence): the
            # highest epoch ever seen must survive restarts so a later
            # promotion mints past it.
            self.epoch = max(self.epoch, int(rec["Epoch"]))
            return None
        if rec.get("Result"):
            # Result record: inline payload as hex, or an offloaded
            # pointer whose bytes are durable in the backend itself.
            if rec.get("Offloaded") and self._result_backend is None:
                # Fail FAST: replaying the pointer without a backend
                # would serve "completed, no result" — restore the
                # store's result_dir config instead.
                raise RuntimeError(
                    f"journal references offloaded result "
                    f"{rec['Key']!r} but no result backend is "
                    f"configured (set result_dir to the same mount "
                    f"it was written to)")
            body = (None if rec.get("Offloaded")
                    else bytes.fromhex(rec.get("ResultHex", "")))
            self._results[rec["Key"]] = (
                body, rec.get("ContentType", "application/json"))
            self._result_keys.setdefault(
                rec["Key"].split(":", 1)[0], set()).add(rec["Key"])
            return
        if rec.get("Evict"):
            # Journal is None during replay, so the subclass's
            # append is a no-op — this just forgets the task. Blob
            # deletes re-run too: a crash between the Evict append
            # and the original deletes leaked them; replay cleans up.
            # EXCEPT KeepBlobs records (rebalance forget): those blobs
            # belong to the shard that imported the range — deleting
            # them here would dangle the new owner's pointers.
            keys = self._apply_evict(rec["TaskId"])
            if not rec.get("KeepBlobs"):
                for key in keys:
                    self._delete_blob(key)
            return
        if rec.get("Slim"):
            # Transition record: body/orig state is untouched (they
            # ride only on upserts), exactly like the live mutation;
            # the journaled timestamp is kept so set scores replay
            # faithfully.
            prev = self._tasks.get(rec["TaskId"])
            if prev is None:
                return None  # compacted-away predecessor
            task = prev.with_status(rec["Status"],
                                    rec.get("BackendStatus"))
            task.publish = False
            task.timestamp = float(rec.get("Timestamp")
                                   or task.timestamp)
            self._remove_from_set(prev)
            self._tasks[task.task_id] = task
            self._add_to_set(task)
            return task
        task = APITask.from_dict(rec)
        task.body = bytes.fromhex(rec.get("BodyHex", ""))
        # Don't re-publish during replay — LocalPlatform.start()
        # re-seeds the broker from unfinished_tasks() afterwards.
        task.publish = False
        InMemoryTaskStore.upsert(self, task)
        # Keep the journaled timestamp (upsert stamps "now"):
        # set scores and the reaper's stuck-task age clock must
        # survive restarts, not reset to replay time.
        stored = self._tasks[task.task_id]
        ts = float(rec.get("Timestamp") or stored.timestamp)
        stored.timestamp = ts
        self._sets[(stored.endpoint_path,
                    stored.canonical_status)][stored.task_id] = ts
        orig = rec.get("OrigHex")
        if orig:
            self._orig_bodies[task.task_id] = (
                bytes.fromhex(orig),
                rec.get("OrigContentType", "application/json"))

    def _log(self, task: APITask, slim: bool = False) -> None:
        # Called with self._lock held (from _apply_*): journal order is
        # exactly mutation order, so replay reconstructs the true final state.
        if self._journal is None or not task.durable:
            return
        rec = task.to_dict()
        if slim:
            # Status transitions never change body/orig — journaling them
            # again would append the (hex-doubled) payload on EVERY
            # transition, ~8x the necessary bytes for a 4-transition task.
            rec["Slim"] = True
        else:
            rec = self._full_record(task)
        self._append(rec)

    def _append(self, rec: dict) -> None:
        # Called with self._lock held; shared by task and result records.
        if self._journal is None:
            return
        self._check_degraded()
        start = time.monotonic()
        line, chain = self._journal_format.encode_record(
            rec, self.chain_head)
        data = line + "\n"
        try:
            self._journal.write(data)
            self._journal.flush()
        except OSError as exc:
            # The record's bytes may be torn or absent on disk: flip to
            # degraded mode and tell the caller to unwind its in-memory
            # mutation (rollback=True) — the store must never acknowledge,
            # or remember, state the journal does not hold.
            raise self._enter_degraded(exc, "append") from exc
        self.chain_head = chain
        nbytes = len(data.encode("utf-8"))
        self._stat_bytes += nbytes
        self._m_appended.inc(nbytes)
        self._fsync_dirty = True
        if self._fsync_kind == "always":
            # Bytes reached the file before the fsync attempt: on failure
            # memory EQUALS the file, so the mutation stays (rollback=False)
            # — only the acknowledgment is refused (at-least-once residual,
            # docs/durability.md#fsync-policies).
            self._fsync_journal()
        elif self._fsync_kind == "group":
            self._group_commit()
        self._record_append_time(time.monotonic() - start)
        self._records += 1
        if (self._records >= self._next_compact_at
                and self._records > 2 * self._live_records()):
            # The append above flushed this mutation to the journal FILE
            # (durable against process death; durable against machine
            # crash only per the fsync policy — docs/durability.md); a
            # failed rewrite (disk full) must not surface as an error for
            # — or skip the notify/publish of — a transition that
            # succeeded. And it must not retry on the very next write (a
            # full O(tasks) rewrite per transition while the disk is
            # already under pressure): back off a full compaction interval
            # either way.
            import logging
            before = self._records
            try:
                self._compact_locked()
                logging.getLogger("ai4e_tpu.taskstore").info(
                    "journal compacted: %d -> %d records (generation %d)",
                    before, self._records, self.journal_generation)
            except OSError:
                logging.getLogger("ai4e_tpu.taskstore").exception(
                    "journal auto-compaction failed; continuing on the "
                    "append-only journal")
            self._next_compact_at = self._records + self._compact_every

    # -- disk-fault degraded mode + fsync policy (docs/durability.md) ------

    def _check_degraded(self) -> None:
        if self.degraded:
            raise JournalDegradedError(
                f"task store is journal-degraded ({self.degraded_reason}); "
                "mutations refused until recover()", rollback=False)

    def _enter_degraded(self, exc: OSError,
                        where: str) -> JournalDegradedError:
        """Flip to fenced read-only degraded mode on a journal disk fault.
        Returns the typed error for the caller to raise; idempotent for
        repeat faults. Reads keep serving; the HTTP surfaces answer
        mutations 503 + ``X-Shed-Reason: journal-degraded``."""
        import errno as errno_mod
        import logging
        name = errno_mod.errorcode.get(exc.errno or 0, "OSError")
        if not self.degraded:
            self.degraded = True
            self.degraded_reason = f"{name} on journal {where}: {exc}"
            self._m_degraded.set(1.0)
            self._m_degraded_total.inc(errno=name)
            logging.getLogger("ai4e_tpu.taskstore").error(
                "journal %s hit %s on %s; store is now DEGRADED "
                "(read-only) — mutations refuse with 503 "
                "journal-degraded until recover() "
                "(docs/durability.md#degraded-mode)",
                self._journal_path, name, where)
        return JournalDegradedError(
            self.degraded_reason or f"{name} on journal {where}",
            rollback=(where == "append"))

    def _fsync_journal(self) -> None:
        """Push flushed journal bytes to stable storage. Caller holds
        ``self._lock``. Raises JournalDegradedError(rollback=False) on
        EIO — the bytes are in the FILE, so memory stays; only the
        acknowledgment is refused."""
        fh = self._journal
        if fh is None or not self._fsync_dirty:
            return
        try:
            # FaultyFile (chaos/disk.py) exposes fsync(); real handles go
            # through os.fsync on the descriptor.
            sync = getattr(fh, "fsync", None)
            if sync is not None:
                sync()
            else:
                os.fsync(fh.fileno())
        except OSError as exc:
            raise self._enter_degraded(exc, "fsync") from exc
        self._fsync_dirty = False
        self._fsync_last = time.monotonic()
        self._stat_fsyncs += 1
        self._m_fsyncs.inc(policy=self._fsync_kind)

    def _group_commit(self) -> None:
        """group:<ms> policy: at most one fsync per window. An append that
        lands with the window already elapsed pays the fsync inline (the
        amortization point — the store lock serializes appends, so one
        fsync covers every record flushed since the last); otherwise a
        timer completes the window so an idle tail is synced within <ms>
        even when no further append arrives. Caller holds ``self._lock``."""
        now = time.monotonic()
        if now - self._fsync_last >= self._fsync_group_s:
            self._fsync_journal()
            return
        if self._fsync_timer is None:
            delay = max(self._fsync_group_s - (now - self._fsync_last),
                        0.001)
            t = threading.Timer(delay, self._timer_fsync)
            t.daemon = True
            self._fsync_timer = t
            t.start()

    def _timer_fsync(self) -> None:
        """Group-commit window completion (timer thread). A fault here
        flips degraded without raising — there is no caller to refuse;
        the appends inside the broken window are the policy's documented
        acknowledged-but-unsynced residual."""
        with self._lock:
            self._fsync_timer = None
            if self._closed or self.degraded or self._journal is None:
                return
            try:
                self._fsync_journal()
            except JournalDegradedError:
                pass  # _enter_degraded already logged + metered

    def _record_append_time(self, seconds: float) -> None:
        self._m_append_s.observe(seconds)
        self._append_times.append(seconds)
        if len(self._append_times) > 4096:
            # Keep the bench-window reservoir bounded; p99 over the most
            # recent half is plenty for the result block.
            del self._append_times[:2048]

    def recover(self) -> bool:
        """Operator/cycle hook: leave degraded mode once the disk is
        healthy again. Re-salvages the journal (the failed append may have
        left a torn tail on disk — exactly the shape boot-salvage
        repairs), reopens the append handle, probes an fsync, and
        re-admits mutations. Returns True when the store is writable on
        exit; False (still degraded) when the disk still faults."""
        with self._lock:
            if self._closed:
                return False
            if not self.degraded:
                return True
            # Discard the broken handle FIRST — before the scan, and
            # without flushing: its write buffer holds exactly the
            # rolled-back record's bytes, and an ordinary close() would
            # re-flush them onto the now-healthy file, resurrecting a
            # mutation the caller was told was refused and unwound
            # (review finding, regression-tested). Whatever partial bytes
            # the failed flush DID land are a torn tail the salvage scan
            # below truncates.
            # A FOLLOWER keeps its append handle in ``_raw`` with
            # ``_journal`` gated off (e.g. a promote() whose epoch mint
            # hit the disk fault and unwound): discard and reopen THAT
            # slot, or the broken buffered handle would survive recovery
            # while a fresh one lands in the wrong attribute.
            follower = getattr(self, "role", "primary") == "follower"
            if follower:
                old, self._raw = self._raw, None
            else:
                old, self._journal = self._journal, None
            if old is not None:
                self._close_discarding(old)
            try:
                scan = self._journal_format.scan_journal(self._journal_path)
                report = self._journal_format.salvage(
                    self._journal_path, scan)
                fh = open(self._journal_path, "a",  # noqa: SIM115
                          encoding="utf-8")
                os.fsync(fh.fileno())
            except (OSError, self._journal_format.JournalCorruptError):
                import logging
                logging.getLogger("ai4e_tpu.taskstore").exception(
                    "journal %s: recovery attempt failed; store stays "
                    "degraded", self._journal_path)
                return False
            if follower:
                self._raw = fh
            else:
                self._journal = fh
            if report is not None:
                # The salvage truncated bytes that were VISIBLE to
                # replication readers (a torn fragment streams like any
                # other bytes): a reader whose offset passed the verified
                # prefix would otherwise be served the middle of a fresh
                # record spliced onto its stale buffer — or report zero
                # lag while missing every post-recover write. The
                # generation bump is the system's one "file bytes
                # changed" signal (compaction's contract); readers
                # full-resync from offset 0 (review finding).
                self.journal_generation += 1
            self.chain_head = scan.chain_head
            self._records = scan.records
            self._fsync_dirty = False
            self.degraded = False
            self.degraded_reason = None
            self._m_degraded.set(0.0)
            import logging
            logging.getLogger("ai4e_tpu.taskstore").warning(
                "journal %s: recovered from degraded mode; mutations "
                "re-admitted at chain head %s", self._journal_path,
                self.chain_head)
            return True

    def journal_stats(self) -> dict:
        """The bench/ops summary block: append volume, fsync/compaction
        counts, and append p99 — docs/durability.md#observability."""
        with self._lock:
            times = sorted(self._append_times)
            p99 = times[int(len(times) * 0.99)] if times else 0.0
            return {
                "bytes_appended": self._stat_bytes,
                "fsyncs": self._stat_fsyncs,
                "compactions": self._stat_compactions,
                "salvages": self._stat_salvages,
                "fsync_policy": (self._fsync_kind
                                 if self._fsync_kind != "group" else
                                 f"group:{self._fsync_group_s * 1000:g}"),
                "append_p99_ms": round(p99 * 1000, 3),
                "degraded": self.degraded,
                "chain_head": self.chain_head,
            }

    def _compact_locked(self) -> None:
        """Rewrite the journal as one full record per live task (+ one per
        result). Caller holds ``self._lock`` (or is still single-threaded in
        __init__). Failure at ANY point leaves the store on a valid journal:
        the replacement file is fully written and its handle opened before
        the atomic rename, and the old handle is closed only after the swap
        succeeds."""
        tmp = self._journal_path + ".compact"
        new_journal = None
        # The rewrite restarts the hash chain from genesis: the compacted
        # file is a new byte lineage (followers already resync on the
        # generation bump; the chain head is per (generation, file)).
        chain = self._journal_format.GENESIS

        def emit(f, rec: dict) -> None:
            nonlocal chain
            line, chain = self._journal_format.encode_record(rec, chain)
            f.write(line + "\n")

        try:
            with open(tmp, "w", encoding="utf-8") as f:
                if self.epoch:
                    # The fencing epoch must survive the rewrite — it is
                    # state, not history.
                    emit(f, {"Epoch": self.epoch})
                for task in self._tasks.values():
                    if not task.durable:
                        # In-memory-only records (cache hits) must not be
                        # promoted to durability by a rewrite.
                        continue
                    emit(f, self._full_record(task))
                # Tasks first, then results — replay applies them in file
                # order and a result's task record must already exist.
                for key, (body, ctype) in self._results.items():
                    owner = self._tasks.get(key.split(":", 1)[0])
                    if owner is not None and not owner.durable:
                        continue
                    emit(f, self._result_record(key, body, ctype))
                f.flush()
                os.fsync(f.fileno())
            # Open the append handle on the tmp file BEFORE the rename: the
            # handle follows the inode, so after os.replace it IS the live
            # journal — no window where a failed reopen leaves a handle
            # pointing at an unlinked file.
            new_journal = open(tmp, "a", encoding="utf-8")  # noqa: SIM115
            os.replace(tmp, self._journal_path)  # atomic swap
        except OSError:
            if new_journal is not None:
                new_journal.close()
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        old = self._journal
        self._journal = new_journal
        self._records = (len(self._tasks) + len(self._results)
                         + (1 if self.epoch else 0))
        self.journal_generation += 1
        self.chain_head = chain
        # The rewrite was fsynced before the rename; nothing unsynced
        # survives from the old file's lineage.
        self._fsync_dirty = False
        self._stat_compactions += 1
        self._m_compactions.inc()
        if old is not None:
            old.close()

    def compact(self) -> None:
        """Force a journal rewrite (operational hook; auto-compaction covers
        normal operation)."""
        with self._lock:
            self._check_open()
            self._compact_locked()

    def _live_records(self) -> int:
        """Journal records a fully-compacted journal would hold — the
        bloat denominator for the compaction heuristics."""
        return len(self._tasks) + len(self._results)

    def _check_open(self) -> None:
        # Degraded refuses BEFORE any memory mutation, with the typed
        # error the HTTP surfaces map to 503 journal-degraded — reads
        # never come through here, so they keep serving.
        super()._check_open()
        if self.degraded:
            self._check_degraded()

    def _apply_set_result(self, key: str, result: bytes | None,
                          content_type: str) -> None:
        # Journal the result so a completed task survives restart WITH its
        # payload — without this a replayed task would report completed
        # while its result is gone (a worse lie than losing the task).
        # Append FIRST, mutate memory second: the base apply deletes a
        # superseded offload blob, which must never happen before the
        # record is known journaled — a degraded append after that delete
        # would roll back to a pointer whose blob is gone, making an
        # acknowledged result unreadable (review finding). Append-first
        # means a failed append leaves memory untouched: nothing to
        # unwind. Pre-validate what the apply would refuse so the journal
        # never holds a record memory rejected.
        self._check_open()
        tid = key.split(":", 1)[0]
        self._check_owner(tid)
        owner = self._tasks.get(tid)
        if owner is None or owner.durable:
            try:
                self._append(
                    self._result_record(key, result, content_type))
            except JournalDegradedError as exc:
                if not exc.rollback:
                    # Fsync-failure shape: the record's bytes ARE in the
                    # file (and on any replica that absorbs the stream).
                    # Apply the memory mutation so memory == file — the
                    # refused-but-possibly-durable at-least-once
                    # residual, the same contract upsert/update keep on
                    # rollback=False (review finding: append-first must
                    # not invert it). The unchecked core: the store is
                    # degraded NOW, so the checked apply would refuse a
                    # mutation whose record is already durable.
                    self._set_result_in_memory(key, result, content_type)
                raise
        # else: the owning record never reached the journal; its result
        # must not either (replay would otherwise restore an orphan
        # result).
        self._set_result_in_memory(key, result, content_type)

    def _apply_evict(self, task_id: str) -> list[str]:
        if task_id not in self._tasks:
            return []
        self._check_open()
        # Capture before the pop: a non-durable record was never journaled,
        # so journaling its eviction would only bloat the file. The rest of
        # the snapshot is the degraded-rollback undo — an eviction whose
        # Evict append fails with possibly-torn bytes must restore the
        # task wholesale, or memory forgets a task the journal still holds
        # (restart/replicas resurrect it) and a recovered retry no-ops
        # before ever journaling the eviction (review finding).
        task = self._tasks[task_id]
        durable = task.durable
        orig = self._orig_bodies.get(task_id)
        ledger = self._ledgers.get(task_id)
        keys = set(self._result_keys.get(task_id, ()))
        results = {key: self._results[key] for key in keys
                   if key in self._results}
        blob_keys = super()._apply_evict(task_id)
        if durable:
            rec = {"Evict": True, "TaskId": task_id}
            if self._forgetting:
                # Rebalance forget: the blobs moved WITH the range — a
                # replay of this record must not delete the new owner's
                # payloads out of the shared backend.
                rec["KeepBlobs"] = True
            try:
                self._append(rec)
            except JournalDegradedError as exc:
                if exc.rollback:
                    self._tasks[task_id] = task
                    self._add_to_set(task)
                    if orig is not None:
                        self._orig_bodies[task_id] = orig
                    if ledger is not None:
                        self._ledgers[task_id] = ledger
                    if keys:
                        self._result_keys[task_id] = keys
                        self._results.update(results)
                    raise
                # Fsync-failure shape: the Evict record IS in the file
                # and memory already forgot the task — the eviction is
                # complete, so fall through and surrender the blob keys.
                # Raising here would leak them forever: nothing
                # references the blobs anymore and the caller's delete
                # loop would never receive the keys (review finding).
                # The sweep's NEXT mutation refuses typed before
                # touching memory, so degradation still surfaces.
        return blob_keys

    def _apply_upsert(self, task: APITask) -> APITask:
        self._check_open()
        prev = self._tasks.get(task.task_id) if task.task_id else None
        had_orig = (task.task_id in self._orig_bodies
                    if task.task_id else False)
        prev_orig = (self._orig_bodies.get(task.task_id)
                     if had_orig else None)
        stored = super()._apply_upsert(task)
        try:
            self._log(stored)
        except JournalDegradedError as exc:
            if exc.rollback:
                self._rollback_upsert(stored, prev, had_orig, prev_orig)
            raise
        return stored

    def _rollback_upsert(self, stored: APITask, prev: APITask | None,
                         had_orig: bool,
                         prev_orig: tuple[bytes, str] | None) -> None:
        """Unwind ONE in-memory upsert whose journal append failed with
        possibly-torn bytes (degraded write path). Caller holds the lock."""
        self._remove_from_set(stored)
        if prev is None:
            self._tasks.pop(stored.task_id, None)
        else:
            self._tasks[prev.task_id] = prev
            self._add_to_set(prev)
        if had_orig:
            self._orig_bodies[stored.task_id] = prev_orig
        else:
            self._orig_bodies.pop(stored.task_id, None)

    def _apply_update(
        self, task_id: str, status: str, backend_status: str | None
    ) -> APITask:
        self._check_open()
        prev = self._tasks.get(task_id)
        task = super()._apply_update(task_id, status, backend_status)
        try:
            self._log(task, slim=True)
        except JournalDegradedError as exc:
            if exc.rollback and prev is not None:
                self._remove_from_set(task)
                self._tasks[task_id] = prev
                self._add_to_set(prev)
            raise
        return task

    def _validates_task_ids(self) -> bool:
        # Journal replay runs before the append handle opens
        # (``self._journal is None``) and follower absorb sets
        # ``_absorbing`` — both apply already-accepted history and must
        # never re-validate it (ADVICE r5: a legacy ':' TaskId would
        # crash-loop replay / wedge absorb forever).
        return self._journal is not None and not self._absorbing

    def _drain_fsync_on_close(self) -> None:
        """Cancel any pending group-commit timer and push the dirty tail
        down on a CLEAN close (a graceful shutdown should not owe the
        disk anything, whatever the policy). Caller holds ``self._lock``;
        best-effort — close must succeed on a faulting disk too."""
        timer, self._fsync_timer = self._fsync_timer, None
        if timer is not None:
            timer.cancel()
        if (self._fsync_kind != "never" and self._fsync_dirty
                and not self.degraded and self._journal is not None):
            try:
                self._fsync_journal()
            except JournalDegradedError:
                pass  # _enter_degraded logged it; close proceeds

    @staticmethod
    def _close_discarding(fh) -> None:
        """Close a DEGRADED journal handle WITHOUT flushing its buffer.

        After a rollback=True append failure the handle's write buffer
        holds exactly the refused record's unflushed bytes — an ordinary
        ``close()`` re-flushes them onto the (possibly healed) file,
        landing a mutation the caller was told was refused and unwound:
        a restart, a replica drain, or ``recover()`` would then resurrect
        it (review finding; regression-tested). The descriptor is
        atomically redirected onto ``os.devnull`` (dup2) BEFORE the
        close, so the close-time flush drains harmlessly there. NOT
        os.close()-then-close(): between those two calls another thread
        (a blob write, a sibling shard's open) can open a file that
        REUSES the freed descriptor number, and the close-time flush
        would splice the refused bytes into that unrelated file (review
        finding). Acknowledged records are never at risk — every
        successful append flushed."""
        try:
            fd = fh.fileno()
        except (OSError, ValueError):
            fd = None
        if fd is not None:
            try:
                devnull = os.open(os.devnull, os.O_WRONLY)
            except OSError:
                devnull = None
            if devnull is not None:
                try:
                    os.dup2(devnull, fd)
                except OSError:
                    pass
                finally:
                    os.close(devnull)
        try:
            fh.close()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        with self._lock:
            if not self._closed and self._journal is not None:
                self._drain_fsync_on_close()
                if self.degraded:
                    self._close_discarding(self._journal)
                else:
                    self._journal.close()
            self._closed = True


class FollowerTaskStore(JournaledTaskStore):
    """Replication follower — the control plane's availability story.

    The reference's task state lives in managed network Redis that any
    component reaches and Azure keeps available (``RedisConnection.cs:12-38``,
    ``deploy_cache_prerequisites.sh:15-31``). This store gives a second
    control-plane replica the same role: it tails the primary's journal
    stream (``replication.py`` pulls ``GET /v1/taskstore/journal``), applies
    each record to its own in-memory state, and appends the raw line to its
    own journal file — byte-compatible with the primary's, so a follower
    restart replays it with the ordinary ``JournaledTaskStore`` machinery.

    While ``role == "follower"`` every external mutation raises
    ``NotPrimaryError`` (the HTTP surface maps it to 503 so store clients
    fail over to the primary); reads — task polls, results, depths — are
    served locally, which also offloads read traffic from the primary.
    ``promote()`` flips it to a live primary: the raw-append handle becomes
    the journal and writes flow.
    """

    # Class-level defaults so the write fence is a no-op while
    # super().__init__ replays the local journal (instance attrs land after).
    role = "primary"
    _absorbing = False
    # The PRIMARY's chain head as verified off the absorbed stream — the
    # value divergence checks compare against the primary's own
    # ``chain_head``. None = unanchored (fresh boot / legacy stream):
    # checksums still verify, the first enveloped line's chain is adopted.
    # Distinct from ``chain_head``, which tracks this replica's OWN file
    # (whose leading epoch line from ``reset`` makes its byte lineage —
    # legitimately — different from the primary's).
    _absorb_chain: str | None = None

    def __init__(self, journal_path: str, start_as_primary: bool = False,
                 **kwargs):
        super().__init__(journal_path, **kwargs)
        self._absorbing = False
        if start_as_primary:
            # Born primary (an HA deployment's active node): behaves exactly
            # like a JournaledTaskStore, plus the demote()/note_epoch()
            # fence so a promoted standby can depose it (VERDICT r4 #3).
            # No epoch is minted — boot is not a failover.
            self._raw = None
            self.role = "primary"
        else:
            # Demote: keep the append handle for raw absorbed lines, but
            # gate self-journaling off (absorbed records are appended
            # verbatim; the _log path must not double-write them).
            self._raw = self._journal
            self._journal = None
            self.role = "follower"

    # -- replication feed ---------------------------------------------------

    def _write_own_line(self, fh, rec: dict) -> None:
        """Append one record to this replica's OWN journal, enveloped
        against its own chain — so the local file is self-consistent for
        its own restart salvage/replay (its byte lineage legitimately
        differs from the primary's by the ``reset`` epoch line). Caller
        holds ``self._lock``; caller flushes."""
        line, self.chain_head = self._journal_format.encode_record(
            rec, self.chain_head)
        fh.write(line + "\n")

    @property
    def replica_chain_head(self) -> str | None:
        """The primary-stream chain head this replica has verified up to —
        compare with the primary's ``chain_head`` for divergence (None
        until the first enveloped line anchors it)."""
        return self._absorb_chain

    def absorb_lines(self, lines: list[str]) -> None:
        """Apply journal lines streamed from the primary and append them
        to the local journal (one flush per call, not per line).
        Replicated Slim transitions notify this replica's own listeners
        (gateway long-poll waiters on the standby must wake when a task
        completes on the primary); full upserts already notify inside
        ``upsert``.

        Every line is checksum- and chain-verified BEFORE anything
        applies: a corrupt streamed line must never absorb silently (it
        would poison this replica with bytes the primary never wrote, or
        ratify the primary's own bit-rot). The verified prefix is applied
        and kept; the bad line and everything after it raise
        ``JournalCorruptError`` — the HTTP replicator answers with a full
        generation-style resync, the in-process shard link parks loudly
        at the offset (``sharding.ShardReplicaLink``). Legacy
        checksum-less lines absorb verbatim for migration."""
        transitions: list[APITask] = []
        error = None
        with self._lock:
            if self.role != "follower":
                raise RuntimeError("absorb after promote — replication "
                                   "must stop when the follower becomes "
                                   "primary")
            self._check_open()
            verified: list[dict] = []
            chain = self._absorb_chain
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec, chain, _legacy = (
                        self._journal_format.verify_line(line, chain))
                except self._journal_format.JournalCorruptError as exc:
                    self._m_verify_fail.inc()
                    error = exc
                    break
                verified.append(rec)
            self._absorbing = True
            try:
                for rec in verified:
                    task = self._apply_replay_record(rec)
                    if task is not None:
                        transitions.append(task)
                    self._write_own_line(self._raw, rec)
                    self._records += 1
            finally:
                self._absorbing = False
            self._raw.flush()
            self._absorb_chain = chain
        for task in transitions:
            self._notify(task)
        if error is not None:
            raise error

    def reset(self) -> None:
        """Discard all replicated state — the primary compacted (journal
        generation changed), so the follower resyncs from offset 0 of the
        rewritten file, which is a full state snapshot."""
        with self._lock:
            if self.role != "follower":
                # Same fence as absorb_lines: a replicator that kept running
                # past a promotion (e.g. the HTTP /promote path racing a
                # poll) must never wipe the newly-promoted primary.
                raise RuntimeError("reset after promote — replication must "
                                   "stop when the follower becomes primary")
            self._check_open()
            self._tasks.clear()
            self._orig_bodies.clear()
            self._results.clear()
            self._result_keys.clear()
            self._sets.clear()
            self._records = 0
            self._raw.close()
            self._raw = open(self._journal_path, "w",  # noqa: SIM115
                             encoding="utf-8")
            # Fresh file, fresh lineages: our own chain restarts at
            # genesis, and the absorbed stream restarts at the primary's
            # genesis (the resync re-reads its file from offset 0).
            self.chain_head = self._journal_format.GENESIS
            self._absorb_chain = self._journal_format.GENESIS
            if self.epoch:
                # The fencing epoch survives the truncation: a crash before
                # the absorbed stream re-delivers the primary's epoch record
                # must not replay this node back to an unfenced epoch 0.
                self._write_own_line(self._raw, {"Epoch": self.epoch})
                self._raw.flush()
                self._records = 1

    def promote(self) -> None:
        """Become the primary: accept writes, journal them normally. The
        caller must stop the replication feed first (``absorb_lines``
        refuses afterwards) and re-seed its transport from
        ``unfinished_tasks()`` — exactly what a restarted platform does.

        Mints the next fencing epoch and journals it: this store's writes
        now belong to a lineage strictly newer than anything the deposed
        primary can claim, and the mint survives restarts (so no two
        promotions ever share an epoch)."""
        with self._lock:
            if self.role == "primary":
                return
            self.role = "primary"
            self._journal = self._raw
            self.epoch += 1
            try:
                self._append({"Epoch": self.epoch})
            except JournalDegradedError as exc:
                if exc.rollback:
                    # The mint never reached the file: unwind WHOLESALE.
                    # A half-promoted store would hold a memory-only
                    # epoch a restart replays away — a later promotion
                    # could then re-mint an epoch this lineage already
                    # claimed, breaking the no-two-promotions-share-an-
                    # epoch fencing guarantee (review finding). Unwound,
                    # the store is an intact (degraded) follower; after
                    # recover() a retried promote() re-mints cleanly.
                    self.epoch -= 1
                    self._journal = None
                    self.role = "follower"
                    raise
                # Fsync-failure shape: the Epoch record IS in the file —
                # the promotion is durable and complete. Swallow: the
                # store is primary and degraded; every subsequent
                # mutation refuses with the typed error anyway.

    def demote(self, epoch: int) -> None:
        """Fence this node out of the primary role: a peer presented
        evidence of a strictly newer primary lineage (``epoch`` greater
        than ours). Writes refuse with ``NotPrimaryError`` from the moment
        this returns; reads stay served. Raises ``StaleEpochError`` when
        the presented epoch is not newer — the CALLER is the stale side
        and must not depose us. Idempotent for an already-demoted node."""
        with self._lock:
            self._check_open()
            if self.role == "follower":
                self.epoch = max(self.epoch, epoch)
                return
            if epoch <= self.epoch:
                raise StaleEpochError(
                    f"demotion epoch {epoch} is not newer than ours "
                    f"({self.epoch}); refusing")
            self.epoch = epoch
            self.role = "follower"
            self._raw = self._journal
            self._journal = None
            # Record the fence so a restart replays epoch >= this value: a
            # rebooted deposed primary can never re-mint an epoch the new
            # primary already holds.
            self._write_own_line(self._raw, {"Epoch": epoch})
            self._raw.flush()
            self._records += 1

    # Whether PASSIVE fencing evidence (X-Store-Epoch request headers, a
    # journal-stream probe's epoch param) may demote this node. True by
    # default — a FollowerTaskStore exists for HA; the platform sets it
    # False on a born-primary with NO configured HA peer, so a solo
    # deployment can never be written out of service by a forged or stale
    # epoch header (there is no standby to take over). The explicit
    # /demote endpoint is unaffected — it is an operator/prober action.
    passive_fencing = True

    # Plausibility bound on PASSIVE fencing evidence (ADVICE r5 #2): an
    # unauthenticated X-Store-Epoch header may only demote us when it is
    # within this many epochs of our own. Epochs advance by 1 per promotion,
    # so a legitimate peer can realistically be at most a few ahead; a
    # forged huge epoch would otherwise be ADOPTED as our own, propagate via
    # honest clients' echoes, and depose the newly-promoted standby too — a
    # one-request total write outage. Evidence beyond the bound is ignored
    # (logged); genuinely large jumps go through the authenticated /demote
    # path, which stays unbounded.
    PASSIVE_EPOCH_BOUND = 8

    def note_epoch(self, epoch: int) -> None:
        """Ingest fencing evidence carried by ordinary traffic (the
        ``X-Store-Epoch`` request header, a journal-stream probe's epoch
        param): a higher epoch means a newer primary exists somewhere —
        self-demote before touching state. Cheap no-op on every request
        where the epoch is not newer (the steady state). Evidence more than
        ``PASSIVE_EPOCH_BOUND`` ahead of our own epoch is implausible from
        an honest peer and is ignored (see the bound's comment)."""
        if not self.passive_fencing:
            return
        if epoch > self.epoch + self.PASSIVE_EPOCH_BOUND:
            import logging
            logging.getLogger("ai4e_tpu.taskstore").warning(
                "ignoring implausible passive fencing epoch %d (ours is %d, "
                "bound +%d); use the authenticated /demote path if this is "
                "a real failover", epoch, self.epoch,
                self.PASSIVE_EPOCH_BOUND)
            return
        if epoch > self.epoch and self.role == "primary":
            try:
                self.demote(epoch)
            except StaleEpochError:
                pass  # raced with a concurrent demotion to a higher epoch

    # -- follower write fence ----------------------------------------------

    def _check_writable(self) -> None:
        if self.role == "follower" and not self._absorbing:
            raise NotPrimaryError(
                "store replica is a follower; writes go to the primary")

    def _apply_upsert(self, task: APITask) -> APITask:
        self._check_writable()
        return super()._apply_upsert(task)

    def _apply_update(self, task_id: str, status: str,
                      backend_status: str | None) -> APITask:
        self._check_writable()
        return super()._apply_update(task_id, status, backend_status)

    def _apply_set_result(self, key: str, result: bytes | None,
                          content_type: str) -> None:
        self._check_writable()
        super()._apply_set_result(key, result, content_type)

    def _apply_evict(self, task_id: str) -> list[str]:
        self._check_writable()
        return super()._apply_evict(task_id)

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                if self.role == "follower" and self._raw is not None:
                    timer, self._fsync_timer = self._fsync_timer, None
                    if timer is not None:
                        timer.cancel()
                    self._raw.close()
                elif self._journal is not None:
                    self._drain_fsync_on_close()
                    if self.degraded:
                        self._close_discarding(self._journal)
                    else:
                        self._journal.close()
            self._closed = True
