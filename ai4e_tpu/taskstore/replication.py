"""Control-plane replication — journal streaming, follower sync, failover.

The reference keeps task state in managed network Redis: any number of
gateway/dispatcher/function instances reach it concurrently and Azure keeps
it available behind a connection-retry policy
(``ProcessManager/Libraries/RedisConnection.cs:12-38``,
``InfrastructureDeployment/deploy_cache_prerequisites.sh:15-31``). This
framework's store is an in-process state machine with a journal — durable
(r3) but single-homed. This module adds the availability half:

- the primary's HTTP surface streams its journal
  (``GET /v1/taskstore/journal?offset=&generation=`` — ``http.py``);
- ``JournalReplicator`` runs next to a ``FollowerTaskStore`` on the standby
  replica, tailing that stream and absorbing each record, so the standby
  holds the full task state (tasks, original bodies, results, status sets)
  a beat behind the primary;
- ``FailoverWatchdog`` probes the primary and, after ``down_after``
  consecutive failures, promotes the follower — writes then flow to the
  standby, and an ``on_promote`` hook lets the host process re-seed its
  transport from ``unfinished_tasks()`` exactly like a restart does.

Semantics and limits (stated, not hidden): replication is asynchronous —
on failover the standby may lag by the last in-flight poll (bounded by the
stream's long-poll turnaround, typically milliseconds); a lost tail means
those tasks are re-created by clients, never half-applied (journal lines
are absorbed whole).

Split-brain fencing is code, not posture (VERDICT r4 #3): promotion mints
a journaled, monotonically-increasing epoch; every store response carries
it (``X-Store-Epoch``), clients echo the highest epoch they have seen on
every request, and a primary that learns of a newer epoch — from a client
header, a journal-stream probe, or this module's ``FencingProber``
knocking on the deposed primary's door — self-demotes and refuses writes
with 503-not-primary (``store.py`` ``FollowerTaskStore.demote``). A
partitioned-not-dead primary therefore stops accepting writes the moment
any fencing evidence reaches it, and rejoins as a follower automatically
when the prober's demote call carries the new primary's URL. This is the
single-writer property the reference bought from managed Redis + sentinel
demotion (``RedisConnection.cs:12-38``), made explicit.
"""

from __future__ import annotations

import asyncio
import logging

import aiohttp

from ..metrics import DEFAULT_REGISTRY
from ..utils.http import SessionHolder
from .journal import JournalCorruptError
from .store import FollowerTaskStore

log = logging.getLogger("ai4e_tpu.taskstore.replication")

JOURNAL_PATH = "/v1/taskstore/journal"


def split_complete_lines(buffer: bytes) -> tuple[list[str], bytes]:
    """Split a journal-stream buffer into the complete lines it holds and
    the unterminated remainder. Journal records are absorbed whole or not
    at all — a chunk boundary mid-record must never half-apply — so every
    tail consumer (the HTTP ``JournalReplicator`` here, the in-process
    per-shard ``ShardReplicaLink`` in ``sharding.py``) shares this one
    split rule."""
    consumed = buffer.rfind(b"\n") + 1
    if not consumed:
        return [], buffer
    return buffer[:consumed].decode("utf-8").splitlines(), buffer[consumed:]


class JournalReplicator:
    """Tail the primary's journal stream into a ``FollowerTaskStore``.

    On (re)connect the follower is reset and resynced from offset 0: the
    primary may have compacted while we were away (generation mismatch),
    and local restart-compaction means our own byte count never equals the
    primary's offset — a full resync is always correct, and the journal is
    control-plane sized (it compacts to one record per live task). While
    the primary is unreachable the follower simply holds its last state —
    promotable at any moment.
    """

    def __init__(self, store: FollowerTaskStore, primary_url: str,
                 poll_wait: float = 10.0, api_key: str | None = None,
                 chunk_limit: int = 4 * 1024 * 1024, metrics=None):
        self.store = store
        self.primary_url = primary_url.rstrip("/")
        self.poll_wait = poll_wait
        self.chunk_limit = chunk_limit
        # Blessed default-resolution idiom (AIL002): the assembly plumbs its
        # own registry; standalone construction falls back to the process
        # default in ONE visible expression, never a conditional rebinding.
        metrics = metrics or DEFAULT_REGISTRY
        self._offset_gauge = metrics.gauge(
            "ai4e_replication_offset_bytes",
            "Journal bytes this follower has absorbed")
        self._lag_gauge = metrics.gauge(
            "ai4e_replication_lag_bytes",
            "Primary journal bytes not yet absorbed (0 = caught up)")
        headers = ({"Ocp-Apim-Subscription-Key": api_key}
                   if api_key else None)
        self._sessions = SessionHolder(headers=headers)
        self._task: asyncio.Task | None = None
        self._stopped = asyncio.Event()
        # Exposed for tests/metrics: bytes applied and the primary's
        # generation we are tracking. -1 = never connected.
        self.offset = 0
        self.generation = -1
        # Set once CAUGHT UP — offset reached the primary's journal size
        # for the current generation. Merely completing one poll is not
        # enough: the initial snapshot can span many chunk_limit-sized
        # polls, and the watchdog must not arm promotion on a follower
        # holding an arbitrary snapshot prefix (ADVICE r4).
        self.synced = asyncio.Event()

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001; ai4e: noqa[AIL005] — awaiting our own cancelled loop; the outcome is irrelevant at teardown
                pass
            self._task = None

    async def _run(self) -> None:
        buffer = b""
        backoff = 0.5
        while not self._stopped.is_set():
            try:
                session = await self._sessions.get()
                params = {"offset": str(self.offset),
                          "generation": str(self.generation),
                          "wait": str(self.poll_wait),
                          "limit": str(self.chunk_limit),
                          # Fencing evidence: if we outlived a failover and
                          # are polling a deposed primary, our higher epoch
                          # demotes it (http.py journal_stream).
                          "epoch": str(self.store.epoch)}
                async with session.get(
                        self.primary_url + JOURNAL_PATH, params=params,
                        timeout=aiohttp.ClientTimeout(
                            total=self.poll_wait + 30)) as resp:
                    if resp.status != 200:
                        raise aiohttp.ClientError(
                            f"journal stream returned {resp.status}")
                    gen = int(resp.headers.get("X-Journal-Generation", "0"))
                    served_from = int(resp.headers.get(
                        "X-Journal-Offset", str(self.offset)))
                    size = int(resp.headers.get("X-Journal-Size", "0"))
                    chunk = await resp.read()
                if gen != self.generation or served_from != self.offset:
                    # Generation change (primary compacted) or first
                    # connect: full resync from the snapshot at offset 0.
                    # A follower mid-resync holds an arbitrary snapshot
                    # prefix — it is NOT a legal promotion target until it
                    # catches up again, even if it was fully synced on the
                    # previous generation.
                    self.synced.clear()
                    if self.generation != -1:
                        log.info("journal generation %s -> %s; resyncing",
                                 self.generation, gen)
                    self.store.reset()
                    buffer = b""
                    self.generation = gen
                    self.offset = served_from
                    if served_from != 0:
                        # Server always restarts mismatched readers at 0;
                        # anything else is a contract violation.
                        raise aiohttp.ClientError(
                            f"journal reset served from offset {served_from}")
                if chunk:
                    lines, buffer = split_complete_lines(buffer + chunk)
                    if lines:
                        # Absorb off the event loop: applying a large resync
                        # chunk is file+dict work that must not stall the
                        # replica's serving loop.
                        await asyncio.to_thread(self.store.absorb_lines, lines)
                    self.offset += len(chunk)
                if self.offset >= size:
                    # Caught up to the primary's journal as of this poll —
                    # only now is this follower a safe promotion target.
                    self.synced.set()
                self._offset_gauge.set(float(self.offset))
                self._lag_gauge.set(float(max(0, size - self.offset)))
                backoff = 0.5
            except asyncio.CancelledError:
                raise
            except JournalCorruptError as exc:
                # A streamed line failed checksum/chain verification
                # (store.absorb_lines): the verified prefix applied;
                # NEVER absorb the bad line silently. Force the
                # generation-mismatch resync path — reset + re-read from
                # offset 0 of the primary's file; transient stream
                # corruption heals on the re-read, persistent primary
                # disk corruption keeps failing loudly here until the
                # primary's own boot-salvage/quarantine (or its next
                # compaction rewrite) repairs the file.
                log.error("journal stream from %s failed VERIFICATION "
                          "(%s); forcing full resync", self.primary_url,
                          exc)
                self.synced.clear()
                self.generation = -1
                buffer = b""
                try:
                    await asyncio.wait_for(self._stopped.wait(), backoff)
                except asyncio.TimeoutError:
                    pass
                backoff = min(backoff * 2, 10.0)
            except Exception as exc:  # noqa: BLE001 — keep tailing through outages
                log.warning("journal stream from %s failed (%s); retrying",
                            self.primary_url, exc)
                self.generation = -1  # force clean resync on reconnect
                try:
                    await asyncio.wait_for(self._stopped.wait(), backoff)
                except asyncio.TimeoutError:
                    pass
                backoff = min(backoff * 2, 10.0)

    async def aclose(self) -> None:
        await self.stop()
        await self._sessions.close()


class FailoverWatchdog:
    """Promote the follower when the primary stops answering.

    Probes ``GET {primary}/v1/taskstore/journal?offset=0&wait=0`` every
    ``interval`` seconds; after ``down_after`` consecutive failures it stops
    replication, promotes the store, and fires ``on_promote`` (the host
    re-seeds dispatch from ``unfinished_tasks()``). The role the reference
    delegated to Azure's managed-Redis availability, made explicit.
    """

    def __init__(self, replicator: JournalReplicator,
                 interval: float = 2.0, down_after: int = 3,
                 on_promote=None):
        self.replicator = replicator
        self.interval = interval
        self.down_after = down_after
        self.on_promote = on_promote
        self.promoted = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._stopped = asyncio.Event()

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001; ai4e: noqa[AIL005] — awaiting our own cancelled loop; the outcome is irrelevant at teardown
                pass
            self._task = None

    async def _probe(self) -> bool:
        try:
            session = await self.replicator._sessions.get()
            async with session.get(
                    self.replicator.primary_url + JOURNAL_PATH,
                    params={"offset": "0", "wait": "0", "limit": "1"},
                    timeout=aiohttp.ClientTimeout(total=5.0)) as resp:
                return resp.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return False

    async def _run(self) -> None:
        failures = 0
        while not self._stopped.is_set():
            try:
                await asyncio.wait_for(self._stopped.wait(), self.interval)
                return
            except asyncio.TimeoutError:
                pass
            if not self.replicator.synced.is_set():
                # Never synced since boot: promoting would crown an EMPTY
                # store (e.g. both replicas rolling, standby ready first —
                # the primary being briefly unreachable at our boot is not
                # a failover). Wait for one full sync before arming.
                continue
            if await self._probe():
                failures = 0
                continue
            failures += 1
            if failures < self.down_after:
                continue
            log.warning("primary %s down after %d probes; promoting follower",
                        self.replicator.primary_url, failures)
            await self.replicator.stop()
            self.replicator.store.promote()
            if self.on_promote is not None:
                res = self.on_promote()
                if asyncio.iscoroutine(res):
                    await res
            self.promoted.set()
            return


class FencingProber:
    """Actively fence the deposed primary after a promotion.

    Passive fencing (clients echoing ``X-Store-Epoch``) closes the
    split-brain window only when fencing evidence happens to reach the old
    primary; this prober closes it deterministically: it polls the peer's
    ``/v1/taskstore/role`` and, whenever the peer claims ``primary`` with
    an epoch older than ours, POSTs ``/v1/taskstore/demote`` with our epoch
    (and ``advertise_url``, so the peer's platform rejoins us as a follower
    automatically — ``platform_assembly.demote_now``). It keeps running for
    the life of the primary: a deposed peer that REBOOTS as primary from
    stale config is re-fenced on the next probe. The sentinel-demotes-the-
    old-master step of the reference's managed-Redis posture, as code."""

    def __init__(self, store, peer_url: str, advertise_url: str | None = None,
                 api_key: str | None = None, interval: float = 2.0):
        self.store = store
        self.peer_url = peer_url.rstrip("/")
        self.advertise_url = advertise_url
        self.interval = interval
        headers = ({"Ocp-Apim-Subscription-Key": api_key}
                   if api_key else None)
        self._sessions = SessionHolder(headers=headers)
        self._task: asyncio.Task | None = None
        self._stopped = asyncio.Event()
        self.fenced = asyncio.Event()  # set each time a demote lands

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001; ai4e: noqa[AIL005] — awaiting our own cancelled loop; the outcome is irrelevant at teardown
                pass
            self._task = None

    async def aclose(self) -> None:
        await self.stop()
        await self._sessions.close()

    async def _probe_once(self) -> None:
        session = await self._sessions.get()
        timeout = aiohttp.ClientTimeout(total=5.0)
        async with session.get(self.peer_url + "/v1/taskstore/role",
                               timeout=timeout) as resp:
            if resp.status != 200:
                return
            peer = await resp.json()
        peer_epoch = int(peer.get("epoch", 0))
        # Two reasons to knock: the peer still claims primary on a stale
        # epoch (fence it), or it was already fenced — e.g. passively, by a
        # client's epoch header — but has no replication feed yet (nudge it
        # to rejoin us; only meaningful when it runs a platform lifecycle
        # and we have a URL to offer).
        needs_fence = (peer.get("role") == "primary"
                       and peer_epoch < self.store.epoch)
        needs_rejoin = (peer.get("role") == "follower"
                        and peer.get("replicating") is False
                        and self.advertise_url is not None
                        and peer_epoch <= self.store.epoch)
        if not (needs_fence or needs_rejoin):
            return
        payload = {"epoch": self.store.epoch}
        if self.advertise_url:
            payload["primary_url"] = self.advertise_url
        if needs_fence:
            log.warning("peer %s still claims primary at epoch %s; fencing "
                        "with epoch %s", self.peer_url, peer_epoch,
                        self.store.epoch)
        async with session.post(self.peer_url + "/v1/taskstore/demote",
                                json=payload, timeout=timeout) as resp:
            if resp.status == 200:
                self.fenced.set()
            elif resp.status == 409:
                # StaleEpochError from the peer: OUR epoch is not newer —
                # this prober is the stale side of the split. Do not keep
                # knocking as if the peer were merely unreachable; the
                # next role probe will show the real epoch and stand down.
                log.warning(
                    "peer %s refused demotion (409): our epoch %s is the "
                    "stale side", self.peer_url, self.store.epoch)

    async def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                await self._probe_once()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — peer unreachable is the normal case
                # Debug, not warning: while the peer is partitioned/down this
                # fires every probe interval for as long as the outage lasts —
                # but the evidence must exist somewhere when fencing is the
                # thing being debugged (AIL005).
                log.debug("fencing probe of %s failed: %s", self.peer_url, exc)
            try:
                await asyncio.wait_for(self._stopped.wait(), self.interval)
                return
            except asyncio.TimeoutError:
                pass
