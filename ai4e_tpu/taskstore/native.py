"""ctypes bindings for the native task-store core (``native/taskstore_core.cpp``).

``NativeTaskStore`` implements the same surface as ``InMemoryTaskStore`` —
upsert / update_status / conditional transitions / results / status-set
queries — backed by the C++ engine: the state machine (the part the reference
ran natively as C# functions over Redis, ``CacheConnectorUpsert.cs:40-213``)
mutates under a C++ mutex without the GIL. Publisher and listener
side-effects stay in Python, driven from the record + publish flag the engine
returns, with the same publish-failure → failed rollback. Drop-in for
``LocalPlatform`` via ``PlatformConfig(native_store=True)``.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Callable, Iterable

from .store import Publisher, StoreSideEffects, TaskNotFound
from .task import APITask, TaskStatus

log = logging.getLogger("ai4e_tpu.taskstore.native")

_SO_NAME = "libtaskstore_core.so"
_SEP = "\x1f"


class _TaskView(ctypes.Structure):
    _fields_ = [
        ("timestamp", ctypes.c_double),
        ("publish", ctypes.c_int32),
        ("task_id", ctypes.c_char_p),
        ("status", ctypes.c_char_p),
        ("backend_status", ctypes.c_char_p),
        ("endpoint", ctypes.c_char_p),
        ("content_type", ctypes.c_char_p),
        ("body", ctypes.POINTER(ctypes.c_uint8)),
        ("body_len", ctypes.c_uint64),
        ("owner", ctypes.c_void_p),
    ]


def build_library(force: bool = False) -> str:
    from ..utils.native_build import build_native_library
    return build_native_library("taskstore_core.cpp", _SO_NAME, force=force)


def _load():
    lib = ctypes.CDLL(build_library())
    view = ctypes.POINTER(_TaskView)
    lib.tsc_create.restype = ctypes.c_void_p
    lib.tsc_destroy.argtypes = [ctypes.c_void_p]
    lib.tsc_upsert.restype = view
    lib.tsc_upsert.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_int]
    lib.tsc_update_status.restype = view
    lib.tsc_update_status.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_char_p]
    lib.tsc_update_status_if.restype = view
    lib.tsc_update_status_if.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p]
    lib.tsc_requeue_if.restype = view
    lib.tsc_requeue_if.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p]
    lib.tsc_get.restype = view
    lib.tsc_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tsc_get_original.restype = view
    lib.tsc_get_original.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tsc_set_result.restype = ctypes.c_int
    lib.tsc_set_result.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_char_p]
    lib.tsc_get_result.restype = view
    lib.tsc_get_result.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tsc_set_len.restype = ctypes.c_uint64
    lib.tsc_set_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p]
    lib.tsc_dump_sets.restype = ctypes.c_void_p  # manual free
    lib.tsc_dump_sets.argtypes = [ctypes.c_void_p]
    lib.tsc_dump_members.restype = ctypes.c_void_p  # manual free
    lib.tsc_dump_members.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_char_p]
    lib.tsc_free_str.argtypes = [ctypes.c_void_p]
    lib.tsc_free_view.argtypes = [view]
    return lib


_lib = None


def get_lib():
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def _buf(data: bytes):
    return ((ctypes.c_uint8 * len(data)).from_buffer_copy(data)
            if data else None)


class NativeTaskStore(StoreSideEffects):
    """InMemoryTaskStore-compatible facade over the C++ engine. Listener +
    publish-failure plumbing is the shared ``StoreSideEffects`` — identical
    semantics to the Python store, no drift."""

    def __init__(self, publisher: Publisher | None = None):
        self._lib = get_lib()
        self._handle = self._lib.tsc_create()
        self._publisher = publisher
        self._listeners: list[Callable[[APITask], None]] = []
        # Result-cache provenance sidecar (rescache/): the C++ record has no
        # CacheKey field, but the store listener contract requires tasks to
        # carry one — without it the cache never fills and single-flight
        # registrations never release, so duplicate requests would coalesce
        # onto a stale (possibly failed) record forever. Kept Python-side,
        # keyed by TaskId; the native store has no Python-side retention
        # reaper, so this map's growth tracks the store's own.
        self._cache_keys: dict[str, str] = {}

    def __del__(self):  # pragma: no cover - interpreter teardown ordering
        try:
            self._lib.tsc_destroy(self._handle)
        except Exception:  # noqa: BLE001; ai4e: noqa[AIL005] — __del__ during interpreter teardown; nothing to report to
            pass

    def _consume(self, view) -> APITask | None:
        if not view:
            return None
        v = view.contents
        body = bytes(ctypes.cast(
            v.body, ctypes.POINTER(ctypes.c_char * v.body_len)).contents) \
            if v.body_len else b""
        task = APITask(
            task_id=v.task_id.decode(),
            timestamp=v.timestamp,
            status=v.status.decode(),
            backend_status=v.backend_status.decode(),
            endpoint=v.endpoint.decode(),
            body=body,
            content_type=v.content_type.decode(),
            publish=bool(v.publish),
        )
        self._lib.tsc_free_view(view)
        task.cache_key = self._cache_keys.get(task.task_id, "")
        return task

    # -- core state machine (InMemoryTaskStore surface) --------------------

    def upsert(self, task: APITask) -> APITask:
        if ":" in task.task_id:
            # Same guard as the Python store: ':' is the result-key stage
            # separator; see InMemoryTaskStore.upsert.
            raise ValueError(
                f"TaskId must not contain ':' (reserved as the result "
                f"stage separator): {task.task_id!r}")
        stored = self._consume(self._lib.tsc_upsert(
            self._handle, task.task_id.encode(), task.endpoint.encode(),
            task.status.encode(), task.backend_status.encode(),
            _buf(task.body), len(task.body), task.content_type.encode(),
            1 if task.publish else 0))
        if task.cache_key:
            # Keyed by the STORED id — the engine assigns the GUID for
            # blank-id creates. An upsert WITHOUT a key keeps the original
            # (the same inheritance the Python store applies across
            # pipeline handoffs).
            self._cache_keys[stored.task_id] = task.cache_key
            stored.cache_key = task.cache_key
        # Snapshot the publisher at transition time (the Python store does
        # this under its lock) so a concurrent set_publisher cannot route
        # this task to a broker the decision wasn't made against.
        publisher = self._publisher if stored.publish else None
        self._notify(stored)
        self._publish_after(stored, publisher)
        return stored

    def update_status(self, task_id: str, status: str,
                      backend_status: str | None = None) -> APITask:
        task = self._consume(self._lib.tsc_update_status(
            self._handle, task_id.encode(), status.encode(),
            None if backend_status is None else backend_status.encode()))
        if task is None:
            raise TaskNotFound(task_id)
        self._notify(task)
        return task

    def update_status_if(self, task_id: str, expected_status: str,
                         status: str,
                         backend_status: str | None = None) -> APITask | None:
        task = self._consume(self._lib.tsc_update_status_if(
            self._handle, task_id.encode(), expected_status.encode(),
            status.encode(),
            None if backend_status is None else backend_status.encode()))
        if task is not None:
            self._notify(task)
        return task

    def requeue_if(self, task_id: str, expected_status: str) -> APITask | None:
        task = self._consume(self._lib.tsc_requeue_if(
            self._handle, task_id.encode(), expected_status.encode()))
        if task is None:
            return None
        publisher = self._publisher if task.publish else None
        self._notify(task)
        self._publish_after(task, publisher)
        return task

    def get(self, task_id: str) -> APITask:
        task = self._consume(self._lib.tsc_get(self._handle,
                                               task_id.encode()))
        if task is None:
            raise TaskNotFound(task_id)
        return task

    def get_original_body(self, task_id: str) -> bytes:
        blob = self._consume(self._lib.tsc_get_original(
            self._handle, task_id.encode()))
        return blob.body if blob is not None else b""

    # -- results -----------------------------------------------------------

    def set_result(self, task_id: str, result: bytes,
                   content_type: str = "application/json",
                   stage: str | None = None) -> None:
        key = task_id if stage is None else f"{task_id}:{stage}"
        ok = self._lib.tsc_set_result(
            self._handle, task_id.encode(), key.encode(),
            _buf(result), len(result), content_type.encode())
        if not ok:
            raise TaskNotFound(task_id)

    def get_result(self, task_id: str,
                   stage: str | None = None) -> tuple[bytes, str] | None:
        key = task_id if stage is None else f"{task_id}:{stage}"
        blob = self._consume(self._lib.tsc_get_result(self._handle,
                                                      key.encode()))
        if blob is None:
            return None
        return blob.body, blob.content_type

    # -- status-set queries -------------------------------------------------

    def set_len(self, endpoint_path: str, status: str) -> int:
        return int(self._lib.tsc_set_len(self._handle,
                                         endpoint_path.encode(),
                                         status.encode()))

    def _sets_rows(self) -> list[tuple[str, str, str]]:
        ptr = self._lib.tsc_dump_sets(self._handle)
        try:
            raw = ctypes.string_at(ptr).decode()
        finally:
            self._lib.tsc_free_str(ptr)
        rows = []
        for line in raw.splitlines():
            parts = line.split(_SEP)
            if len(parts) >= 3:
                rows.append((parts[0], parts[1], parts[2]))
        return rows

    def set_members(self, endpoint_path: str, status: str) -> list[str]:
        # Per-set native query — the reaper sweeps one set per endpoint, so
        # a full-store dump per call would be O(endpoints) serializations.
        ptr = self._lib.tsc_dump_members(self._handle,
                                         endpoint_path.encode(),
                                         status.encode())
        try:
            raw = ctypes.string_at(ptr).decode()
        finally:
            self._lib.tsc_free_str(ptr)
        return [line.split(_SEP)[0] for line in raw.splitlines() if line]

    def endpoints(self) -> list[str]:
        return sorted({path for path, _, _ in self._sets_rows()})

    def depths(self) -> dict[str, dict[str, int]]:
        out: dict[str, dict[str, int]] = {}
        for path, status, tid in self._sets_rows():
            bucket = out.setdefault(path, {s: 0 for s in TaskStatus.ALL})
            if tid:
                bucket[status] += 1
        return out

    # -- iteration (restart reseed parity) ----------------------------------

    def snapshot(self) -> Iterable[APITask]:
        return [self.get(tid) for _, _, tid in self._sets_rows() if tid]

    def unfinished_tasks(self) -> list[APITask]:
        out = []
        for path, status, tid in self._sets_rows():
            if not tid or status in TaskStatus.TERMINAL:
                continue
            task = self.get(tid)
            if not task.body:
                blob = self._consume(self._lib.tsc_get_original(
                    self._handle, tid.encode()))
                if blob is not None:
                    task.body, task.content_type = blob.body, blob.content_type
            out.append(task)
        return out
