"""HTTP facade over the task store.

The reference exposes the CacheManager as two Azure Functions —
``CacheConnectorUpsert`` (POST task JSON) and ``CacheConnectorGet``
(``GET ?taskId=``) — that every other component calls over HTTPS
(``ProcessManager/CacheManager/CacheConnectorUpsert.cs:40``,
``CacheConnectorGet.cs:26-74``). This module is the same surface as an aiohttp
app, so services on other hosts can share one task store:

- ``POST /v1/taskstore/upsert``   — create/transition a task (task JSON body)
- ``POST /v1/taskstore/update``   — atomic status-only transition by TaskId
  (fixes the read-modify-write race SURVEY.md §5 flags in the reference's
  ``distributed_api_task.py:29-56``)
- ``GET  /v1/taskstore/task?taskId=…`` — poll a task (204 if absent)
- ``GET  /v1/taskstore/depths``   — per-endpoint status-set depths (autoscale signal)

Journaled stores additionally serve the replication surface
(``replication.py`` — the availability slot managed Redis filled for the
reference):

- ``GET  /v1/taskstore/journal?offset=&generation=&wait=`` — stream journal
  bytes from ``offset`` (long-polls up to ``wait`` s when caught up); a
  generation mismatch (the journal was compacted) restarts the reader at
  offset 0 with ``X-Journal-Generation``/``X-Journal-Offset`` headers;
- ``POST /v1/taskstore/promote`` — flip a follower replica to primary
  (manual failover; with a platform ``lifecycle`` this runs the full
  watchdog sequence — replication stopped first, transport started);
- ``POST /v1/taskstore/demote`` ``{"epoch": N, "primary_url": ...}`` —
  fence a stale primary out of the role (split-brain closure; 409 when
  the epoch is not newer). ``primary_url`` triggers automatic rejoin as
  a follower;
- ``GET  /v1/taskstore/role`` — role + fencing epoch + whether a
  replication feed is running.

Mutations against a follower replica return 503 ``{"error": "not primary"}``
with ``X-Not-Primary: 1`` so store clients fail over (and ONLY on that
marker — a plain 503 must not re-home clients to a lagging follower).
Every response carries the fencing epoch (``X-Store-Epoch``); any request
may echo it back, and a primary that sees a newer epoch self-demotes
before the handler touches state (``replication.py`` module docs).
"""

from __future__ import annotations

import asyncio
import json
import os

from aiohttp import web

from .store import (InMemoryTaskStore, JournalDegradedError, NotOwnerError,
                    NotPrimaryError, StaleEpochError, TaskNotFound)
from .task import APITask, TaskStatus


def make_app(store: InMemoryTaskStore,
             app: web.Application | None = None,
             max_body_bytes: int = 128 * 1024 * 1024,
             max_result_bytes: int | None = None,
             lifecycle=None) -> web.Application:
    """Build the task-store surface; pass ``app`` to attach the routes to an
    existing application (e.g. the gateway's, so one control-plane port
    serves both). ``max_body_bytes`` caps task/transition write bodies on
    this surface (0 = unlimited): the gateway app it often rides on disables
    aiohttp's own cap (its published routes enforce per-route edge caps
    incrementally), so these handlers must bound their own buffering.
    ``max_result_bytes`` caps result uploads separately — batch results are
    the payloads the offload backend exists for and are routinely larger
    than request bodies; None defaults to 8× the body cap.

    ``lifecycle`` (optional) receives role changes the HTTP surface
    triggers: ``await lifecycle.promote_now()`` for ``POST /promote`` and
    ``await lifecycle.demote_now(epoch, primary_url)`` for
    ``POST /demote`` — the platform stops/starts its replicator, watchdog
    and transport around the store flip (``platform_assembly.py``).
    Without it the handlers flip the bare store.

    Split-brain fencing (VERDICT r4 #3): every response carries
    ``X-Store-Epoch``, every request may carry it back, and a primary that
    sees a newer epoch in any request self-demotes BEFORE the handler
    touches state — ordinary client traffic propagates the fence."""
    if app is None:
        app = web.Application()
    if max_result_bytes is None:
        max_result_bytes = 8 * max_body_bytes

    from ..utils.http import read_body_limited

    def stamped(handler):
        """Fencing wrapper for every taskstore route: ingest epoch evidence
        from the request, stamp our epoch on the response."""
        async def wrapper(request: web.Request):
            hdr = request.headers.get("X-Store-Epoch")
            if hdr:
                note = getattr(store, "note_epoch", None)
                if note is not None:
                    try:
                        note(int(hdr))
                    except ValueError:
                        pass
            resp = await handler(request)
            epoch = getattr(store, "epoch", None)
            # StreamResponses are already prepared (headers sent) by the
            # time the handler returns — only stamp unsent responses.
            if epoch is not None and not getattr(resp, "prepared", False):
                resp.headers["X-Store-Epoch"] = str(epoch)
            return resp
        return wrapper

    def too_large(limit: int) -> web.Response:
        return web.json_response(
            {"error": f"body exceeds {limit} bytes"}, status=413)

    def not_primary() -> web.Response:
        # 503 (not 4xx): the write is valid, THIS replica can't take it —
        # clients with a replica list rotate to the primary (task_manager).
        # The header distinguishes this from an overload/draining 503,
        # which must NOT make clients rotate to a lagging follower.
        return web.json_response({"error": "not primary"}, status=503,
                                 headers={"X-Not-Primary": "1"})

    def not_owner(exc: NotOwnerError) -> web.Response:
        # Keyspace-range fence (a live slot move in the multi-process rig,
        # or any write-fenced store): the verb is valid, THIS store no
        # longer owns the TaskId's slot. 409 + X-Not-Owner tells ring
        # clients to re-fetch the fence table and re-route — the wire
        # analogue of the sharded facade's NotOwnerError re-route
        # (ai4e_tpu/rig/wire.py RingStoreClient).
        return web.json_response({"error": f"not owner: {exc}"},
                                 status=409,
                                 headers={"X-Not-Owner": "1"})

    def journal_degraded(exc: JournalDegradedError) -> web.Response:
        # Disk fault flipped the store to read-only degraded mode
        # (docs/durability.md#degraded-mode): mutations refuse with a
        # TYPED 503 — X-Shed-Reason names the cause so dashboards and
        # the admission/resilience layers see "dark node", not generic
        # overload. Deliberately NO X-Not-Primary: clients must not
        # re-home reads off a store that is still serving them.
        return web.json_response(
            {"error": f"journal degraded: {exc}"}, status=503,
            headers={"X-Shed-Reason": "journal-degraded",
                     "Retry-After": "5"})

    async def upsert(request: web.Request) -> web.Response:
        raw = await read_body_limited(request, max_body_bytes)
        if raw is None:
            return too_large(max_body_bytes)
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        task = APITask.from_dict(payload)
        # Existing-task transition if a TaskId was supplied and known; otherwise
        # create (CacheConnectorUpsert.cs decides the same way, :90-108).
        from .task import SUB_TASK_SEP
        if SUB_TASK_SEP in task.task_id:
            # Pipeline stage sub-task namespace ("{root}~{stage}",
            # pipeline/spec.py): transitions of EXISTING sub-records are
            # legitimate (a stage worker's saturation requeue rides this
            # surface), but a CREATE would let a caller forge a sub-record
            # that aliases a running pipeline's stage — the coordinator
            # would adopt the forged task's terminal outcome as the stage
            # result. Only the in-process coordinator mints these ids.
            try:
                store.get(task.task_id)
            except TaskNotFound:
                return web.json_response(
                    {"error": f"TaskId must not contain {SUB_TASK_SEP!r} "
                              "(reserved for pipeline stage sub-tasks)"},
                    status=400)
        try:
            task = store.upsert(task)
        except ValueError as exc:  # reserved characters in a supplied TaskId
            return web.json_response({"error": str(exc)}, status=400)
        except NotOwnerError as exc:
            return not_owner(exc)
        except NotPrimaryError:
            return not_primary()
        except JournalDegradedError as exc:
            return journal_degraded(exc)
        return web.json_response(store.get(task.task_id).to_dict())

    async def update(request: web.Request) -> web.Response:
        raw = await read_body_limited(request, max_body_bytes)
        if raw is None:
            return too_large(max_body_bytes)
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        task_id = payload.get("TaskId", "")
        status = payload.get("Status", "")
        if not task_id or not status:
            return web.json_response({"error": "TaskId and Status required"}, status=400)
        expected = payload.get("ExpectedStatus")
        try:
            if expected:
                # Conditional transition (``update_status_if``): the wire
                # form of the suspension-point atomicity contract
                # (docs/concurrency.md) — a remote worker completing a
                # task over this surface would otherwise only have the
                # reachably-racy probe-then-write shape; the condition
                # evaluates under the store lock instead. 409 = the
                # precondition no longer holds (typically a concurrent
                # duplicate already transitioned the task).
                task = store.update_status_if(task_id, expected, status,
                                              payload.get("BackendStatus"))
                if task is None:
                    try:
                        current = store.get(task_id).status
                    except TaskNotFound:
                        return web.Response(status=204)
                    return web.json_response(
                        {"error": "status precondition failed",
                         "Status": current}, status=409)
            else:
                task = store.update_status(task_id, status,
                                           payload.get("BackendStatus"))
        except TaskNotFound:
            return web.Response(status=204)
        except NotOwnerError as exc:
            return not_owner(exc)
        except NotPrimaryError:
            return not_primary()
        except JournalDegradedError as exc:
            return journal_degraded(exc)
        return web.json_response(task.to_dict())

    async def redrive(request: web.Request) -> web.Response:
        """Re-dispatch dead-lettered tasks — the ops surface the reference
        outsourced to Azure Service Bus tooling (dead-letter queues are
        inspected/resubmitted with Service Bus Explorer; here the body is
        retained by the store's ORIG replay, so a redrive is just
        ``requeue_if(task_id, "failed")``: flip back to created and
        republish the original payload through the transport).

        Body ``{"TaskId": ...}`` redrives one task (409 if it is not in a
        failed state — completed/running tasks are never re-run). An empty
        body sweeps: every failed task whose status prose contains
        ``Contains`` (default "delivery attempts exhausted" — the exact
        text the platform writes when a message exhausts its delivery
        budget) is redriven. Pass ``{"Contains": ""}`` to redrive ALL
        failed tasks, including ones that failed in model code."""
        raw = await read_body_limited(request, max_body_bytes)
        if raw is None:
            return too_large(max_body_bytes)
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        if not isinstance(payload, dict):
            return web.json_response(
                {"error": "body must be a JSON object"}, status=400)
        if getattr(store, "role", "primary") == "follower":
            # Refuse up front: an empty sweep would otherwise 200 on a
            # follower (nothing to requeue → no write to fence), hiding
            # from the operator that they redrove the wrong replica.
            return not_primary()
        try:
            task_id = payload.get("TaskId")
            if task_id:
                task = store.requeue_if(task_id, "failed")
                if task is None:
                    try:
                        current = store.get(task_id)
                    except TaskNotFound:
                        return web.json_response(
                            {"error": "unknown task"}, status=404)
                    return web.json_response(
                        {"error": "task is not failed",
                         "Status": current.status}, status=409)
                return web.json_response(task.to_dict())
            contains = payload.get("Contains",
                                   TaskStatus.DEAD_LETTER_PROSE)
            redriven = []
            for ep in store.endpoints():
                for tid in store.set_members(ep, "failed"):
                    try:
                        current = store.get(tid)
                    except TaskNotFound:
                        continue  # evicted between scan and fetch
                    if contains and contains not in current.status:
                        continue
                    if store.requeue_if(tid, "failed") is not None:
                        redriven.append(tid)
        except NotOwnerError as exc:
            return not_owner(exc)
        except NotPrimaryError:
            return not_primary()
        except JournalDegradedError as exc:
            return journal_degraded(exc)
        return web.json_response(
            {"redriven": len(redriven), "task_ids": redriven})

    async def get_task(request: web.Request) -> web.Response:
        task_id = request.query.get("taskId") or request.match_info.get("task_id", "")
        if not task_id:
            return web.json_response({"error": "taskId required"}, status=400)
        try:
            task = store.get(task_id)
        except TaskNotFound:
            return web.Response(status=204)  # CacheConnectorGet.cs:65
        return web.json_response(task.to_dict())

    async def depths(_: web.Request) -> web.Response:
        return web.json_response(store.depths())

    async def put_result(request: web.Request) -> web.Response:
        task_id = request.query.get("taskId", "")
        if not task_id:
            return web.json_response({"error": "taskId required"}, status=400)
        body = await read_body_limited(request, max_result_bytes)
        if body is None:
            return too_large(max_result_bytes)
        try:
            store.set_result(task_id, body,
                             content_type=request.content_type
                             or "application/json",
                             stage=request.query.get("stage") or None)
        except TaskNotFound:
            # Unknown task must be an error, not a silent 204: the worker
            # treats 2xx as "stored".
            return web.json_response({"error": f"unknown task {task_id}"},
                                     status=404)
        except NotOwnerError as exc:
            return not_owner(exc)
        except NotPrimaryError:
            return not_primary()
        except JournalDegradedError as exc:
            return journal_degraded(exc)
        return web.json_response({"ok": True})

    async def get_result(request: web.Request) -> web.Response:
        task_id = request.query.get("taskId", "")
        if not task_id:
            return web.json_response({"error": "taskId required"}, status=400)
        opener = getattr(store, "open_result", None)
        if opener is None:  # stores without streaming (native): buffer
            found = store.get_result(task_id,
                                     stage=request.query.get("stage") or None)
            if found is None:
                return web.Response(status=204)
            body, content_type = found
            return web.Response(body=body, content_type=content_type)
        found = opener(task_id, stage=request.query.get("stage") or None)
        if found is None:
            return web.Response(status=204)
        fh, content_type, size = found
        # Stream in chunks: an offloaded multi-MB batch output must not
        # buffer whole in server memory per concurrent download.
        resp = web.StreamResponse(
            headers={"Content-Type": content_type,
                     "Content-Length": str(size)})
        try:
            # prepare() inside the handle's try: a client that drops the
            # connection here must not leak the blob fd.
            await resp.prepare(request)
            loop = asyncio.get_running_loop()
            while True:
                # Reads off the event loop: on a GCS-FUSE-backed root each
                # read is a network syscall, and blocking here would stall
                # every concurrent request on the shared port.
                chunk = await loop.run_in_executor(None, fh.read, 256 * 1024)
                if not chunk:
                    break
                await resp.write(chunk)
        finally:
            fh.close()
        await resp.write_eof()
        return resp

    app.router.add_post("/v1/taskstore/upsert", stamped(upsert))
    app.router.add_post("/v1/taskstore/update", stamped(update))
    app.router.add_post("/v1/taskstore/redrive", stamped(redrive))
    app.router.add_get("/v1/taskstore/task", stamped(get_task))
    app.router.add_get("/v1/taskstore/task/{task_id}", stamped(get_task))
    app.router.add_get("/v1/taskstore/depths", stamped(depths))
    async def put_result_ref(request: web.Request) -> web.Response:
        """Register a direct-to-storage result: the worker wrote the blob to
        the shared backend itself; only this tiny pointer crosses the
        control network (the reference's containers-write-to-blob-storage
        architecture)."""
        raw = await read_body_limited(request, max_body_bytes)
        if raw is None:
            return too_large(max_body_bytes)
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        task_id = payload.get("TaskId", "")
        if not task_id:
            return web.json_response({"error": "TaskId required"}, status=400)
        register = getattr(store, "set_result_ref", None)
        if register is None:  # e.g. the native store: no ref support
            return web.json_response(
                {"error": "store does not support result refs"}, status=400)
        try:
            store.set_result_ref(
                task_id,
                content_type=payload.get("ContentType")
                or "application/json",
                stage=payload.get("Stage") or None)
        except TaskNotFound:
            return web.json_response({"error": f"unknown task {task_id}"},
                                     status=404)
        except FileNotFoundError as exc:
            # Pointer before blob — a registration race or a mis-mounted
            # worker; 409 so the worker fails loudly instead of serving a
            # dangling pointer.
            return web.json_response({"error": str(exc)}, status=409)
        except NotOwnerError as exc:
            return not_owner(exc)
        except NotPrimaryError:
            return not_primary()
        except JournalDegradedError as exc:
            return journal_degraded(exc)
        except RuntimeError as exc:  # store has no backend configured
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response({"ok": True})

    async def append_ledger(request: web.Request) -> web.Response:
        """Hop-ledger append (observability/ledger.py): a remote worker
        ships its buffered device-phase/batch events here in one POST so
        the task's timeline is complete across process boundaries.
        Events are sanitized, never trusted verbatim; unknown tasks are
        404 (the worker drops the stamp — fail-open telemetry)."""
        raw = await read_body_limited(request, max_body_bytes)
        if raw is None:
            return too_large(max_body_bytes)
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            return web.json_response({"error": "invalid JSON"}, status=400)
        task_id = payload.get("TaskId", "")
        if not task_id:
            return web.json_response({"error": "TaskId required"},
                                     status=400)
        append = getattr(store, "append_ledger", None)
        if append is None:  # e.g. the native store: no ledger support
            return web.json_response(
                {"error": "store does not support the hop ledger"},
                status=404)
        from ..observability.ledger import validate_events
        events = validate_events(payload.get("Events"))
        try:
            kept = append(task_id, events)
        except TaskNotFound:
            return web.json_response({"error": f"unknown task {task_id}"},
                                     status=404)
        except NotOwnerError as exc:
            return not_owner(exc)
        except NotPrimaryError:
            return not_primary()
        except JournalDegradedError as exc:
            return journal_degraded(exc)
        return web.json_response({"ok": True, "appended": kept})

    async def get_ledger(request: web.Request) -> web.Response:
        task_id = request.query.get("taskId", "")
        if not task_id:
            return web.json_response({"error": "taskId required"},
                                     status=400)
        getter = getattr(store, "get_ledger", None)
        events = getter(task_id) if getter is not None else []
        return web.json_response({"TaskId": task_id, "Events": events})

    app.router.add_post("/v1/taskstore/result", stamped(put_result))
    app.router.add_post("/v1/taskstore/result-ref", stamped(put_result_ref))
    app.router.add_get("/v1/taskstore/result", stamped(get_result))
    app.router.add_post("/v1/taskstore/ledger", stamped(append_ledger))
    app.router.add_get("/v1/taskstore/ledger", stamped(get_ledger))

    # -- shard topology (sharded facade only; taskstore/sharding.py) -------

    if getattr(store, "ring", None) is not None:
        async def shards(_: web.Request) -> web.Response:
            """Ring layout + per-shard epoch/role/feed state — what an
            operator (or a future shard-aware client) needs to see where
            the keyspace lives and which fencing epoch each shard is on."""
            return web.json_response(store.topology())

        app.router.add_get("/v1/taskstore/shards", stamped(shards))

    # -- replication surface (journaled stores only; replication.py) -------

    journal_path = getattr(store, "_journal_path", None)
    if journal_path is not None:
        async def journal_stream(request: web.Request) -> web.Response:
            """Serve raw journal bytes from ``offset`` for the follower's
            tail loop. A generation mismatch — the journal was compacted and
            byte offsets invalidated — restarts the reader at offset 0 of
            the current file (which is a full state snapshot)."""
            try:
                offset = int(request.query.get("offset", "0"))
                generation = int(request.query.get("generation", "-1"))
                wait = min(float(request.query.get("wait", "0")), 55.0)
                limit = min(int(request.query.get(
                    "limit", str(4 * 1024 * 1024))), 64 * 1024 * 1024)
                peer_epoch = int(request.query.get("epoch", "0"))
            except ValueError:
                return web.json_response({"error": "bad query"}, status=400)
            if peer_epoch:
                # A follower probing us with a newer epoch is fencing
                # evidence too (e.g. a standby re-pointed at a deposed
                # primary after a failover it lived through).
                note = getattr(store, "note_epoch", None)
                if note is not None:
                    note(peer_epoch)

            deadline = asyncio.get_event_loop().time() + wait
            while True:
                # Snapshot generation + open under the store lock: compaction
                # swaps the file under the same lock, so a handle opened here
                # is consistent with the generation we report.
                with store._lock:
                    gen = store.journal_generation
                    if generation != gen or offset < 0:
                        served_from = 0
                    else:
                        served_from = offset
                    try:
                        fh = open(journal_path, "rb")  # noqa: ASYNC230  # local journal open under the store lock; generation/offset consistency needs it
                    except FileNotFoundError:
                        fh = None
                try:
                    if fh is None:
                        chunk = b""
                        size = 0
                    else:
                        size = os.fstat(fh.fileno()).st_size
                        if served_from > size:
                            # Offset beyond the file without a generation
                            # bump — only possible via truncation outside
                            # the store; restart the reader.
                            served_from = 0
                        fh.seek(served_from)
                        chunk = fh.read(limit)
                finally:
                    if fh is not None:
                        fh.close()
                if chunk or asyncio.get_event_loop().time() >= deadline:
                    return web.Response(
                        body=chunk,
                        content_type="application/x-ndjson",
                        headers={"X-Journal-Generation": str(gen),
                                 "X-Journal-Offset": str(served_from),
                                 "X-Journal-Size": str(size)})
                # Coarse poll while caught up: replication lag tolerance is
                # seconds, so 4 Hz keeps the per-follower open/fstat/lock
                # cost negligible on the primary's event loop.
                await asyncio.sleep(0.25)

        async def promote(_: web.Request) -> web.Response:
            """Manual failover. With a platform lifecycle attached this runs
            the FULL promotion sequence — stop replicator + watchdog, flip
            the store (minting the next fencing epoch), start transport,
            re-seed dispatch — the same path the watchdog takes; a bare
            store flip alone would leave the replicator running, and its
            next resync would try to wipe the new primary (the store's
            role fences now make that a loud error, not data loss)."""
            if lifecycle is not None:
                await lifecycle.promote_now()
            else:
                promote_fn = getattr(store, "promote", None)
                if promote_fn is None:
                    return web.json_response(
                        {"error": "store is not a follower replica"},
                        status=400)
                promote_fn()
            return web.json_response(
                {"ok": True, "role": "primary",
                 "epoch": getattr(store, "epoch", 0)})

        async def demote(request: web.Request) -> web.Response:
            """Fence this node out of the primary role (a promoted standby's
            prober calls this with its newer epoch; operators can too).
            409 when the presented epoch is not newer — the caller is the
            stale side. ``primary_url``, when given, lets the platform
            rejoin the new primary as a follower automatically."""
            raw = await read_body_limited(request, max_body_bytes)
            if raw is None:
                return too_large(max_body_bytes)
            try:
                payload = json.loads(raw or b"{}")
                epoch = int(payload["epoch"])
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                return web.json_response(
                    {"error": "integer 'epoch' required"}, status=400)
            if getattr(store, "demote", None) is None:
                return web.json_response(
                    {"error": "store has no replica role support"},
                    status=400)
            try:
                if lifecycle is not None:
                    await lifecycle.demote_now(
                        epoch, payload.get("primary_url") or None)
                else:
                    store.demote(epoch)
            except StaleEpochError as exc:
                return web.json_response({"error": str(exc)}, status=409)
            return web.json_response(
                {"ok": True, "role": store.role, "epoch": store.epoch})

        async def role(_: web.Request) -> web.Response:
            # "replicating" tells a fencing prober whether a demoted node
            # still needs the rejoin nudge (demote + primary_url); None
            # when no platform lifecycle is attached (bare store — nothing
            # to rejoin with).
            replicating = (None if lifecycle is None
                           else getattr(lifecycle, "replicator", None)
                           is not None)
            return web.json_response(
                {"role": getattr(store, "role", "primary"),
                 "epoch": getattr(store, "epoch", 0),
                 "replicating": replicating,
                 "generation": store.journal_generation,
                 # Durable-truth introspection (docs/durability.md): the
                 # journal's hash-chain head — equal bytes ⇔ equal heads,
                 # so primary/standby divergence is a string comparison —
                 # and whether a disk fault has this store refusing
                 # mutations. A follower's OWN file legitimately diverges
                 # from the primary's once it has re-seeded (reset writes
                 # an epoch line), so the divergence check compares the
                 # primary's chain_head against the follower's
                 # replica_chain_head — the primary-STREAM head it has
                 # verified up to (review finding: comparing chain_head
                 # to chain_head false-alarms after any failover).
                 "chain_head": getattr(store, "chain_head", None),
                 "replica_chain_head": getattr(
                     store, "replica_chain_head", None),
                 "degraded": bool(getattr(store, "degraded", False))})

        app.router.add_get("/v1/taskstore/journal", stamped(journal_stream))
        app.router.add_post("/v1/taskstore/promote", stamped(promote))
        app.router.add_post("/v1/taskstore/demote", stamped(demote))
        app.router.add_get("/v1/taskstore/role", stamped(role))
    return app
