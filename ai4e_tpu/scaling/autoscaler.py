"""Autoscaler — the reference's HPA feedback loop, in-framework.

The reference closes its scaling loop across four external systems
(SURVEY.md §3.5): request counters and queue depths flow to App Insights
(``CurrentProcessingUpsert.cs:100-106``, ``QueueLogger.cs:21-47``), the
azure-k8s-metrics-adapter republishes them as k8s custom metrics
(``deploy_custom_metrics_adapter.sh:6-52``), an HPA per API divides the
metric by a per-replica target (``APIs/Charts/templates/async-gpu/
autoscaler.yaml:11-21`` — 1-10 replicas, queue-depth target 1), and the
cluster autoscaler grows node pools (``deploy_aks.sh:99-109``).

Here the loop is one in-process controller: the scaling signal is the task
store's per-endpoint ``created`` depth (the same ``{path}_created`` sorted
set the reference scrapes) plus in-flight counts, the decision rule is the
k8s HPA algorithm (proportional scaling with a tolerance dead-band and a
scale-down stabilization window), and the actuator is a ``ScaleTarget`` —
live dispatcher-loop fan-out for single-host serving, or a callback that
resizes worker processes / requests TPU slices in a real deployment.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass
from typing import Callable, Protocol

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry

log = logging.getLogger("ai4e_tpu.autoscaler")


@dataclass
class AutoscalePolicy:
    """HPA-shaped policy (autoscaler.yaml:11-21 uses min 1 / max 10 /
    queue-depth target 1)."""

    min_replicas: int = 1
    max_replicas: int = 10
    target_per_replica: float = 1.0   # targetAverageValue
    tolerance: float = 0.1            # k8s HPA default dead-band (10%)
    stabilization_seconds: float = 30.0  # scale-down damping window


class HPADecider:
    """The k8s HPA decision rule: ``desired = ceil(current * metric /
    (replicas * target))`` with a tolerance dead-band, clamped to
    [min, max]; scale-down takes the *maximum* recommendation over the
    stabilization window so a transient dip never kills replicas."""

    def __init__(self, policy: AutoscalePolicy,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self._clock = clock
        self._recommendations: list[tuple[float, int]] = []

    def desired(self, current_replicas: int, metric_value: float) -> int:
        p = self.policy
        current_replicas = max(current_replicas, 1)
        ratio = metric_value / (current_replicas * p.target_per_replica)
        if abs(ratio - 1.0) <= p.tolerance:
            raw = current_replicas
        else:
            raw = math.ceil(current_replicas * ratio)
        raw = min(max(raw, p.min_replicas), p.max_replicas)

        now = self._clock()
        self._recommendations.append((now, raw))
        horizon = now - p.stabilization_seconds
        self._recommendations = [(t, r) for t, r in self._recommendations
                                 if t >= horizon]
        if raw < current_replicas:
            # Scale-down stabilization: act on the window's max.
            raw = min(max(r for _, r in self._recommendations),
                      current_replicas)
        return raw


def predictive_signal(depth_fn: Callable[[], float],
                      arrival_rate_fn: Callable[[], float],
                      drain_rate_fn: Callable[[], float],
                      horizon_s: float = 10.0) -> Callable[[], float]:
    """Projected backlog ``horizon_s`` ahead — the predictive scaling
    signal (docs/orchestration.md).

    ``depth + max(0, arrival - drain) × horizon``: when arrivals outrun
    the drain, the projection grows BEFORE raw depth does, so the HPA
    rule scales up ahead of the queue wait that causes the first
    deadline miss instead of after it. A draining queue projects its
    current depth only (no negative term — scale-down damping belongs to
    the decider's stabilization window, not to the signal).

    The rate inputs are the admission controller's existing estimators
    (``arrival_rate`` / ``drain_rate``) — no new measurement, just a new
    reading of it.
    """
    def signal() -> float:
        growth = max(0.0, float(arrival_rate_fn()) - float(drain_rate_fn()))
        return float(depth_fn()) + growth * horizon_s
    return signal


class ScaleTarget(Protocol):
    """An actuator the controller drives."""

    @property
    def replicas(self) -> int: ...

    def scale_to(self, n: int) -> None: ...


class DispatcherScaleTarget:
    """Scales a dispatcher's delivery-loop count — the single-host stand-in
    for pod replicas: more loops = more tasks in flight feeding the
    micro-batcher = bigger device batches."""

    def __init__(self, dispatcher):
        self.dispatcher = dispatcher

    @property
    def replicas(self) -> int:
        return self.dispatcher.concurrency

    def scale_to(self, n: int) -> None:
        self.dispatcher.set_concurrency(n)


class _ControlLoop:
    """Shared autoscale machinery: the ``ai4e_autoscale_*`` instruments,
    the decide → log → count → actuate step, and the periodic-task
    lifecycle — one copy, so a fix to any of them reaches both the
    single-route and the sharded controller."""

    interval: float = 5.0
    _loop_name: str = "autoscale"

    def _make_instruments(self, metrics: MetricsRegistry | None) -> None:
        # The assembly passes ITS registry here; the `or` fallback is for
        # direct construction in scripts — either way every series this
        # controller emits lands in one registry (AIL002).
        self.metrics = metrics or DEFAULT_REGISTRY
        self._replica_gauge = self.metrics.gauge(
            "ai4e_autoscale_replicas", "Actuated replica count per endpoint")
        self._signal_gauge = self.metrics.gauge(
            "ai4e_autoscale_signal", "Scaling signal value per endpoint")
        self._decisions = self.metrics.counter(
            "ai4e_autoscale_decisions_total",
            "Actuated scaling decisions by endpoint and direction")
        self._task: asyncio.Task | None = None

    def _apply_decision(self, name: str, decider: HPADecider, value: float,
                        current: int, scale_fn) -> int:
        desired = decider.desired(current, value)
        self._signal_gauge.set(value, endpoint=name)
        if desired != current:
            log.info("autoscale %s: signal=%.1f replicas %d -> %d",
                     name, value, current, desired)
            self._decisions.inc(endpoint=name,
                                direction="up" if desired > current
                                else "down")
            scale_fn(desired)
        return desired

    def tick(self):
        raise NotImplementedError

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — control loop must survive
                log.exception("autoscale tick failed for %s",
                              self._loop_name)


class AutoscaleController(_ControlLoop):
    """Periodic control loop: signal → HPA decision → actuator.

    ``signal`` defaults to queue pressure for the endpoint: tasks waiting in
    the ``created`` state set plus tasks being processed (``running``) —
    the reference's scaling metric pair (``TaskQueueLogger.cs:19-27`` depth
    + ``CURRENT_REQUESTS`` in-flight counter) collapsed into one number.
    """

    def __init__(self, store, endpoint_path: str, target: ScaleTarget,
                 policy: AutoscalePolicy | None = None,
                 interval: float = 5.0,
                 signal: Callable[[], float] | None = None,
                 metrics: MetricsRegistry | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.store = store
        self.endpoint_path = endpoint_path
        self._loop_name = endpoint_path
        self.target = target
        self.policy = policy or AutoscalePolicy()
        self.interval = interval
        self.signal = signal or self._default_signal
        self.decider = HPADecider(self.policy, clock=clock)
        self._make_instruments(metrics)

    def _default_signal(self) -> float:
        return (self.store.set_len(self.endpoint_path, "created")
                + self.store.set_len(self.endpoint_path, "running"))

    def tick(self) -> int:
        """One control step (sync; also called by the async loop)."""
        desired = self._apply_decision(
            self.endpoint_path, self.decider, float(self.signal()),
            self.target.replicas, self.target.scale_to)
        self._replica_gauge.set(self.target.replicas,
                                endpoint=self.endpoint_path)
        return desired


class ShardScaleTarget:
    """ONE actuator over a sharded route's per-shard dispatchers.

    PR 6 refused autoscale policies on sharded routes outright: an
    HPA loop per sub-queue plus the admission controller would have been
    several control loops fighting one set of actuators. This object is
    the relaxation's actuator half — per-shard *decisions* (the
    controller below) are applied through this single target, which is
    also a plain ``ScaleTarget`` (``replicas``/``scale_to`` treat the
    shard set as one pool, splitting evenly with the remainder on the
    lowest-indexed shards)."""

    def __init__(self, dispatchers: list):
        if not dispatchers:
            raise ValueError("ShardScaleTarget needs at least one dispatcher")
        self.dispatchers = list(dispatchers)

    @property
    def replicas(self) -> int:
        return sum(d.concurrency for d in self.dispatchers)

    def scale_to(self, n: int) -> None:
        base, rem = divmod(max(0, n), len(self.dispatchers))
        for i, d in enumerate(self.dispatchers):
            d.set_concurrency(base + (1 if i < rem else 0))

    def shard_replicas(self, i: int) -> int:
        return self.dispatchers[i].concurrency

    def scale_shard(self, i: int, n: int) -> None:
        self.dispatchers[i].set_concurrency(max(0, n))


class ShardedAutoscaleController(_ControlLoop):
    """Per-shard scaling decisions through one actuator (the PR 6
    shards-vs-autoscale refusal, relaxed — requires orchestration, see
    ``platform_assembly.register_internal_route``).

    One control loop; per sub-queue, its own signal and its own
    ``HPADecider`` (each shard's scale-down stabilization history is
    independent — one hot shard must not pin a cold shard's loops up),
    all actuated through a single ``ShardScaleTarget``. Instruments,
    decision step, and lifecycle are the shared ``_ControlLoop``
    machinery; the sub-queue is the endpoint label."""

    def __init__(self, shards: list, target: ShardScaleTarget,
                 policy: AutoscalePolicy | None = None,
                 interval: float = 5.0,
                 metrics: MetricsRegistry | None = None,
                 clock: Callable[[], float] = time.monotonic):
        # shards: [(sub_queue_name, signal_fn)] aligned with the target's
        # dispatcher list.
        if len(shards) != len(target.dispatchers):
            raise ValueError(
                f"{len(shards)} shard signals for "
                f"{len(target.dispatchers)} dispatchers")
        self.shards = list(shards)
        self._loop_name = (shards[0][0] if shards else "sharded")
        self.target = target
        self.policy = policy or AutoscalePolicy()
        self.interval = interval
        self.deciders = [HPADecider(self.policy, clock=clock)
                         for _ in self.shards]
        self._make_instruments(metrics)

    def tick(self) -> None:
        for i, (name, signal) in enumerate(self.shards):
            self._apply_decision(
                name, self.deciders[i], float(signal()),
                self.target.shard_replicas(i),
                lambda n, i=i: self.target.scale_shard(i, n))
            self._replica_gauge.set(self.target.shard_replicas(i),
                                    endpoint=name)
