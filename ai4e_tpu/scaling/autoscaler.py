"""Autoscaler — the reference's HPA feedback loop, in-framework.

The reference closes its scaling loop across four external systems
(SURVEY.md §3.5): request counters and queue depths flow to App Insights
(``CurrentProcessingUpsert.cs:100-106``, ``QueueLogger.cs:21-47``), the
azure-k8s-metrics-adapter republishes them as k8s custom metrics
(``deploy_custom_metrics_adapter.sh:6-52``), an HPA per API divides the
metric by a per-replica target (``APIs/Charts/templates/async-gpu/
autoscaler.yaml:11-21`` — 1-10 replicas, queue-depth target 1), and the
cluster autoscaler grows node pools (``deploy_aks.sh:99-109``).

Here the loop is one in-process controller: the scaling signal is the task
store's per-endpoint ``created`` depth (the same ``{path}_created`` sorted
set the reference scrapes) plus in-flight counts, the decision rule is the
k8s HPA algorithm (proportional scaling with a tolerance dead-band and a
scale-down stabilization window), and the actuator is a ``ScaleTarget`` —
live dispatcher-loop fan-out for single-host serving, or a callback that
resizes worker processes / requests TPU slices in a real deployment.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass
from typing import Callable, Protocol

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry

log = logging.getLogger("ai4e_tpu.autoscaler")


@dataclass
class AutoscalePolicy:
    """HPA-shaped policy (autoscaler.yaml:11-21 uses min 1 / max 10 /
    queue-depth target 1)."""

    min_replicas: int = 1
    max_replicas: int = 10
    target_per_replica: float = 1.0   # targetAverageValue
    tolerance: float = 0.1            # k8s HPA default dead-band (10%)
    stabilization_seconds: float = 30.0  # scale-down damping window


class HPADecider:
    """The k8s HPA decision rule: ``desired = ceil(current * metric /
    (replicas * target))`` with a tolerance dead-band, clamped to
    [min, max]; scale-down takes the *maximum* recommendation over the
    stabilization window so a transient dip never kills replicas."""

    def __init__(self, policy: AutoscalePolicy,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self._clock = clock
        self._recommendations: list[tuple[float, int]] = []

    def desired(self, current_replicas: int, metric_value: float) -> int:
        p = self.policy
        current_replicas = max(current_replicas, 1)
        ratio = metric_value / (current_replicas * p.target_per_replica)
        if abs(ratio - 1.0) <= p.tolerance:
            raw = current_replicas
        else:
            raw = math.ceil(current_replicas * ratio)
        raw = min(max(raw, p.min_replicas), p.max_replicas)

        now = self._clock()
        self._recommendations.append((now, raw))
        horizon = now - p.stabilization_seconds
        self._recommendations = [(t, r) for t, r in self._recommendations
                                 if t >= horizon]
        if raw < current_replicas:
            # Scale-down stabilization: act on the window's max.
            raw = min(max(r for _, r in self._recommendations),
                      current_replicas)
        return raw


class ScaleTarget(Protocol):
    """An actuator the controller drives."""

    @property
    def replicas(self) -> int: ...

    def scale_to(self, n: int) -> None: ...


class DispatcherScaleTarget:
    """Scales a dispatcher's delivery-loop count — the single-host stand-in
    for pod replicas: more loops = more tasks in flight feeding the
    micro-batcher = bigger device batches."""

    def __init__(self, dispatcher):
        self.dispatcher = dispatcher

    @property
    def replicas(self) -> int:
        return self.dispatcher.concurrency

    def scale_to(self, n: int) -> None:
        self.dispatcher.set_concurrency(n)


class AutoscaleController:
    """Periodic control loop: signal → HPA decision → actuator.

    ``signal`` defaults to queue pressure for the endpoint: tasks waiting in
    the ``created`` state set plus tasks being processed (``running``) —
    the reference's scaling metric pair (``TaskQueueLogger.cs:19-27`` depth
    + ``CURRENT_REQUESTS`` in-flight counter) collapsed into one number.
    """

    def __init__(self, store, endpoint_path: str, target: ScaleTarget,
                 policy: AutoscalePolicy | None = None,
                 interval: float = 5.0,
                 signal: Callable[[], float] | None = None,
                 metrics: MetricsRegistry | None = None):
        self.store = store
        self.endpoint_path = endpoint_path
        self.target = target
        self.policy = policy or AutoscalePolicy()
        self.interval = interval
        self.signal = signal or self._default_signal
        self.decider = HPADecider(self.policy)
        metrics = metrics or DEFAULT_REGISTRY
        self._replica_gauge = metrics.gauge(
            "ai4e_autoscale_replicas", "Actuated replica count per endpoint")
        self._signal_gauge = metrics.gauge(
            "ai4e_autoscale_signal", "Scaling signal value per endpoint")
        self._task: asyncio.Task | None = None

    def _default_signal(self) -> float:
        return (self.store.set_len(self.endpoint_path, "created")
                + self.store.set_len(self.endpoint_path, "running"))

    def tick(self) -> int:
        """One control step (sync; also called by the async loop)."""
        value = float(self.signal())
        current = self.target.replicas
        desired = self.decider.desired(current, value)
        self._signal_gauge.set(value, endpoint=self.endpoint_path)
        if desired != current:
            log.info("autoscale %s: signal=%.1f replicas %d -> %d",
                     self.endpoint_path, value, current, desired)
            self.target.scale_to(desired)
        self._replica_gauge.set(self.target.replicas,
                                endpoint=self.endpoint_path)
        return desired

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — control loop must survive
                log.exception("autoscale tick failed for %s",
                              self.endpoint_path)
