from .autoscaler import (
    AutoscaleController,
    AutoscalePolicy,
    DispatcherScaleTarget,
    HPADecider,
    ScaleTarget,
)

__all__ = [
    "AutoscaleController",
    "AutoscalePolicy",
    "DispatcherScaleTarget",
    "HPADecider",
    "ScaleTarget",
]
