from .autoscaler import (
    AutoscaleController,
    AutoscalePolicy,
    DispatcherScaleTarget,
    HPADecider,
    ScaleTarget,
    ShardScaleTarget,
    ShardedAutoscaleController,
    predictive_signal,
)

__all__ = [
    "AutoscaleController",
    "AutoscalePolicy",
    "DispatcherScaleTarget",
    "HPADecider",
    "ScaleTarget",
    "ShardScaleTarget",
    "ShardedAutoscaleController",
    "predictive_signal",
]
