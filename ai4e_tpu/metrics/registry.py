"""Metrics registry — counters, gauges, histograms with label dims.

Replaces the reference's App-Insights funnel (``AppInsightsLogger.cs:26-95``,
``CurrentProcessingUpsert.cs:26-113``, ``QueueLogger.cs:21-47``) with an
in-process registry exported in Prometheus text format. Metrics are first-class
here because the autoscaler consumes them (SURVEY.md §3.5): the in-flight
request gauge and per-endpoint queue depths are the scaling signal.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

LabelKey = tuple[tuple[str, str], ...]


def _label_key(labels: dict[str, str] | None) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[LabelKey, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] += amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def collect(self):
        with self._lock:
            return [("counter", self.name, dict(k), v) for k, v in self._values.items()]


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict[LabelKey, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] += amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def collect(self):
        with self._lock:
            return [("gauge", self.name, dict(k), v) for k, v in self._values.items()]


class Histogram:
    """Fixed-bucket latency histogram (seconds)."""

    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0, 30.0, 60.0, float("inf"))

    def __init__(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.buckets = tuple(buckets)
        self._counts: dict[LabelKey, list[int]] = {}
        self._sums: dict[LabelKey, float] = defaultdict(float)
        # OpenMetrics exemplars: (labelkey, bucket index) -> the LAST
        # observation that landed there carrying an exemplar — so a p99
        # bucket in /metrics links to a concrete trace/task id an
        # operator can feed straight to the trace CLI or the flight
        # recorder. Only populated by callers that pass one; the default
        # exposition is byte-identical without them.
        self._exemplars: dict[LabelKey, dict[int, tuple[dict, float, float]]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: dict | None = None,
                **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    if exemplar:
                        self._exemplars.setdefault(key, {})[i] = (
                            dict(exemplar), value, time.time())
                    break
            self._sums[key] += value

    def quantile(self, q: float, **labels: str) -> float:
        """Approximate quantile from bucket boundaries (upper edge)."""
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            if not counts:
                return 0.0
            total = sum(counts)
            target = q * total
            run = 0
            for i, c in enumerate(counts):
                run += c
                if run >= target:
                    return self.buckets[i]
            return self.buckets[-1]

    def collect(self):
        with self._lock:
            out = []
            for key, counts in self._counts.items():
                data = {"buckets": list(zip(self.buckets, counts)),
                        "sum": self._sums[key], "count": sum(counts)}
                exemplars = self._exemplars.get(key)
                if exemplars:
                    # Keyed extension: consumers reading only
                    # buckets/sum/count are untouched.
                    data["exemplars"] = dict(exemplars)
                out.append(("histogram", self.name, dict(key), data))
            return out


class Timer:
    def __init__(self, hist: Histogram, **labels: str):
        self.hist, self.labels = hist, labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0, **self.labels)
        return False


class MetricsRegistry:
    """Named registry; the service shell, broker, and runtime all share one."""

    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_, buckets), Histogram)

    def _get_or_create(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def render_prometheus(self) -> str:
        """Prometheus text exposition — the surface the autoscaler scrapes
        (replaces App Insights + azure-k8s-metrics-adapter,
        ``deploy_custom_metrics_adapter.sh:6-52``)."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        kind_by_cls = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {kind_by_cls[type(m)]}")
            for kind, name, labels, value in m.collect():
                label_s = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
                label_s = "{" + label_s + "}" if label_s else ""
                if kind == "histogram":
                    cum = 0
                    exemplars = value.get("exemplars") or {}
                    for i, (edge, c) in enumerate(value["buckets"]):
                        cum += c
                        le = "+Inf" if edge == float("inf") else repr(edge)
                        inner = dict(labels, le=le)
                        ls = ",".join(f'{k}="{v}"' for k, v in sorted(inner.items()))
                        lines.append(f"{name}_bucket{{{ls}}} {cum}")
                        if i in exemplars:
                            # Exemplar as a standalone COMMENT line right
                            # under its bucket: the classic Prometheus
                            # text format (which this endpoint serves)
                            # has no exemplar syntax — appending
                            # OpenMetrics' `# {…}` after the VALUE would
                            # fail the whole scrape the moment one
                            # exemplar lands. A full-line comment is
                            # skipped by every classic parser while
                            # humans and tooling still get the
                            # bucket→trace/task link. Absent entirely
                            # unless an observation carried one, so the
                            # default exposition stays byte-identical.
                            ex_labels, ex_value, ex_ts = exemplars[i]
                            exs = ",".join(f'{k}="{v}"' for k, v
                                           in sorted(ex_labels.items()))
                            lines.append(
                                f"# exemplar {name}_bucket{{{ls}}} "
                                f"{{{exs}}} {ex_value} {ex_ts}")
                    lines.append(f"{name}_sum{label_s} {value['sum']}")
                    lines.append(f"{name}_count{label_s} {value['count']}")
                else:
                    lines.append(f"{name}{label_s} {value}")
        return "\n".join(lines) + "\n"


DEFAULT_REGISTRY = MetricsRegistry()
