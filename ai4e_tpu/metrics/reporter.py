"""Request reporter — the cross-replica in-flight request counter.

The reference's RequestReporter is a pair of Azure Functions over Redis:
``CurrentProcessingUpsert`` atomically INCRs ``CURRENT_REQUESTS/{cluster}{path}``
by ``IncrementBy − DecrementBy`` and tracks the value as a metric
(``ProcessManager/RequestReporter/CurrentProcessingUpsert.cs:26-113``, model
``ProcessingUpdate.cs:9-15``); ``CurrentProcessingGet`` reads it back
(``CurrentProcessingGet.cs:27-78``). Every API service POSTs on request
start/finish (``APIs/1.0/base-py/ai4e_service.py:135-156``), and the
azure-k8s-metrics-adapter exposes the metric to the HPA
(``APIs/Charts/templates/appinsights-metric.yaml:1-7``) — it is the platform's
*aggregated* (cross-replica) load signal, distinct from each replica's local
in-flight gauge.

Here the same component is one aiohttp app over a thread-safe counter table:

- ``POST /v1/processing``  {Cluster, Path, IncrementBy, DecrementBy} → new value;
- ``GET  /v1/processing?cluster=&path=`` → current value;
- ``GET  /metrics`` exports every counter as ``ai4e_current_requests`` gauge
  samples, which is what the queue-depth autoscaler (``scaling.autoscaler``)
  and an HPA-style external scaler consume.

``ProcessingReporterClient`` is the in-service side: fire-and-forget deltas the
way ``ai4e_service.increment/decrement_requests`` POSTs, so a slow reporter
never blocks the request path.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time

import aiohttp
from aiohttp import web

from ..utils.http import SessionHolder
from .registry import DEFAULT_REGISTRY, MetricsRegistry

log = logging.getLogger("ai4e_tpu.reporter")


class ProcessingCounters:
    """Thread-safe counter table — the Redis ``StringIncrement`` role
    (``CurrentProcessingUpsert.cs:103``).

    Robustness against the two realistic failure modes of a fire-and-forget
    delta stream:

    - the raw sum is kept UNclamped so a decrement that overtakes its
      increment (independent async POSTs can reorder) nets back to zero —
      clamping the stored value would convert each reorder into permanent
      +1 drift of the autoscaling signal;
    - *reads* clamp at zero and treat counters idle for ``stale_after``
      seconds as zero, so a reporter restart mid-flight (raw sum goes
      negative forever) or lost decrements (raw sum stuck positive) both
      decay to a correct quiescent signal instead of permanently skewing
      the HPA input.
    """

    def __init__(self, metrics: MetricsRegistry | None = None,
                 stale_after: float = 600.0):
        self._values: dict[tuple[str, str], tuple[int, float]] = {}
        self._lock = threading.Lock()
        self.stale_after = stale_after
        self.metrics = metrics or DEFAULT_REGISTRY
        self._gauge = self.metrics.gauge(
            "ai4e_current_requests",
            "Cross-replica in-flight requests per cluster/path")

    def adjust(self, cluster: str, path: str,
               increment: int = 0, decrement: int = 0) -> int:
        delta = increment - decrement
        now = time.monotonic()
        with self._lock:
            raw, ts = self._values.get((cluster, path), (0, now))
            if now - ts > self.stale_after:
                raw = 0  # stale residue (lost deltas / restart skew)
            raw += delta
            self._values[(cluster, path)] = (raw, now)
        value = max(0, raw)
        self._gauge.set(value, cluster=cluster, path=path)
        return value

    def value(self, cluster: str, path: str) -> int:
        with self._lock:
            raw, ts = self._values.get((cluster, path), (0, time.monotonic()))
        if time.monotonic() - ts > self.stale_after:
            return 0
        return max(0, raw)

    def snapshot(self) -> dict[tuple[str, str], int]:
        with self._lock:
            keys = list(self._values)
        return {k: self.value(*k) for k in keys}


class RequestReporterService:
    """The reporter as a deployable HTTP component (one per cluster, like the
    reference's function app, ``deploy_request_reporter_function.sh``)."""

    def __init__(self, counters: ProcessingCounters | None = None,
                 metrics: MetricsRegistry | None = None):
        self.metrics = metrics or DEFAULT_REGISTRY
        self.counters = counters or ProcessingCounters(self.metrics)
        self.app = web.Application()
        self.app.router.add_post("/v1/processing", self._upsert)
        self.app.router.add_get("/v1/processing", self._get)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_get("/healthz", self._health)

    async def _upsert(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.Response(status=400, text="bad processing update")
        cluster = body.get("Cluster", "")
        path = body.get("Path", "")
        if not path:
            # Reference validates the update object (CurrentProcessingUpsert.cs:55-66).
            return web.Response(status=400, text="Path is required")
        value = self.counters.adjust(
            cluster, path,
            increment=int(body.get("IncrementBy", 0)),
            decrement=int(body.get("DecrementBy", 0)))
        return web.json_response({"Cluster": cluster, "Path": path,
                                  "CurrentRequests": value})

    async def _get(self, request: web.Request) -> web.Response:
        cluster = request.query.get("cluster", "")
        path = request.query.get("path", "")
        if not path:
            return web.Response(status=400, text="path is required")
        return web.json_response({
            "Cluster": cluster, "Path": path,
            "CurrentRequests": self.counters.value(cluster, path)})

    async def _metrics(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response({"status": "healthy"})


class ProcessingReporterClient:
    """In-service reporter hook: fire-and-forget deltas to the reporter URI
    (``ai4e_service.py:135-156`` builds the same POST from
    ``REQUEST_REPORTER_URI``; failures are logged, never raised — a dead
    reporter must not take the data path down with it)."""

    def __init__(self, reporter_uri: str, cluster: str = "local"):
        self.reporter_uri = reporter_uri.rstrip("/")
        self.cluster = cluster
        self._sessions = SessionHolder(timeout=10.0)
        self._pending: set[asyncio.Task] = set()

    def report(self, path: str, increment: int = 0, decrement: int = 0) -> None:
        """Schedule the delta POST on the running loop; no-op off-loop."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            log.debug("reporter delta for %s dropped: no running loop", path)
            return
        t = loop.create_task(self._send(path, increment, decrement))
        self._pending.add(t)
        t.add_done_callback(self._pending.discard)

    async def _send(self, path: str, increment: int, decrement: int) -> None:
        payload = {"Cluster": self.cluster, "Path": path,
                   "IncrementBy": increment, "DecrementBy": decrement}
        try:
            session = await self._sessions.get()
            async with session.post(f"{self.reporter_uri}/v1/processing",
                                    json=payload) as resp:
                await resp.read()
                if resp.status != 200:
                    log.warning("reporter returned %d for %s", resp.status, path)
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            log.warning("reporter unreachable: %s", exc)

    async def current(self, path: str) -> int | None:
        """Read the aggregated counter back (CurrentProcessingGet.cs:27-78)."""
        try:
            session = await self._sessions.get()
            async with session.get(
                f"{self.reporter_uri}/v1/processing",
                params={"cluster": self.cluster, "path": path}) as resp:
                if resp.status != 200:
                    return None
                return (await resp.json())["CurrentRequests"]
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return None

    async def drain(self, timeout: float = 5.0) -> None:
        if self._pending:
            await asyncio.wait(list(self._pending), timeout=timeout)

    async def close(self) -> None:
        for t in list(self._pending):
            t.cancel()
        if self._pending:
            await asyncio.gather(*self._pending, return_exceptions=True)
        await self._sessions.close()
