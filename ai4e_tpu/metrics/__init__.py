from .registry import (
    DEFAULT_REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Timer,
)

__all__ = [
    "DEFAULT_REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Timer",
]
