from .registry import (
    DEFAULT_REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Timer,
)
from .reporter import (
    ProcessingCounters,
    ProcessingReporterClient,
    RequestReporterService,
)

__all__ = [
    "DEFAULT_REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ProcessingCounters",
    "ProcessingReporterClient",
    "RequestReporterService",
    "Timer",
]
