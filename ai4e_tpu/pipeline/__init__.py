"""First-class pipeline DAGs (docs/pipelines.md).

``PipelineSpec`` declares a DAG of named stages (edges, fan-out/fan-in
joins with a failure quorum, per-stage deadline fractions);
``PipelineCoordinator`` executes it under one client-visible TaskId
through the existing store/broker/dispatcher fabric, reusing the result
cache per stage; ``TaskEventHub`` feeds the gateway's streaming surface
(``GET /v1/taskmanagement/task/{id}/events``) with stage-by-stage
partial results.
"""

from .coordinator import PipelineCoordinator
from .events import TaskEventHub, TaskEventStream, sse_encode
from .spec import (PipelineSpec, PipelineSpecError, StageSpec,
                   split_sub_task_id, stage_deadline, sub_task_id)

__all__ = [
    "PipelineCoordinator",
    "PipelineSpec",
    "PipelineSpecError",
    "StageSpec",
    "TaskEventHub",
    "TaskEventStream",
    "split_sub_task_id",
    "sse_encode",
    "stage_deadline",
    "sub_task_id",
]
