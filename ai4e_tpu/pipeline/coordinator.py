"""Pipeline coordinator — executes declared DAGs over the existing fabric.

One coordinator per platform assembly (``PlatformConfig(pipeline=True)``).
It owns no transport and no execution of its own; every mechanism is a
reuse of what PRs 1–8 built:

- the **root task** is an ordinary gateway-created task whose endpoint is
  the spec's internal entry path (``PipelineSpec.entry_path``) — the store
  publishes it onto the broker queue the coordinator consumes, so restart
  re-seeding (journal replay → ``unfinished_tasks`` republish) IS the
  resume path, with no coordinator-private durability;
- each **stage** runs as a store sub-record ``{root}~{stage}`` dispatched
  through the stage endpoint's ordinary dispatcher — admission deadline
  drops, resilience retries/failover, orchestration placement, and hop
  ledgers all apply to stage work because it *is* ordinary work;
- **stage results** land under the root TaskId's result-stage keys
  (``{root}:{stage}`` — the surface the reference's ensembles already
  used for intermediate outputs), which doubles as the resume marker: a
  relaunched run treats any present stage result as a completed stage;
- the **stage cache** is the inference result cache (``rescache/``) keyed
  on the stage endpoint's family + the canonical stage input hash, so a
  re-run pipeline (same payload) skips completed stages — and a worker
  checkpoint reload invalidates exactly the stages that model serves
  (the family IS the endpoint path the reload hook already invalidates);
- **budget carving**: each stage's sub-task carries
  ``stage_deadline(...)`` — its declared fraction of the request's
  remaining ``X-Deadline-Ms`` budget — and the coordinator sheds a stage
  whose budget is already spent BEFORE dispatch (``expired`` root, never
  a corpse through the broker), the same admission contract every other
  hop honors;
- **streaming**: every stage transition publishes onto the
  ``TaskEventHub`` (``events.py``) feeding the gateway's SSE surface,
  and the first stage completion is the run's time-to-first-partial
  (``ai4e_pipeline_ttfp_seconds``).
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import time

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..observability import ledger as hop
from ..rescache.keys import request_key
from ..taskstore import APITask, TaskNotFound, TaskStatus
from .events import INLINE_RESULT_BYTES, STAGE, TaskEventHub
from .spec import (JoinInput, PipelineSpec, StageState, initial_states,
                   split_sub_task_id, stage_deadline, sub_task_id)

log = logging.getLogger("ai4e_tpu.pipeline")


class PipelineCoordinator:
    """Drives registered ``PipelineSpec``s; one consumer loop per entry
    queue, one in-memory run per live root task."""

    def __init__(self, store, broker, hub: TaskEventHub | None = None,
                 result_cache=None, admission=None, observability=None,
                 metrics: MetricsRegistry | None = None,
                 queue_names=None):
        self.store = store
        self.broker = broker
        self.hub = hub
        self.result_cache = result_cache
        self.admission = admission
        self.observability = observability
        self.metrics = metrics or DEFAULT_REGISTRY
        # entry path -> [queue names] (shard sub-queues under a sharded
        # store; the identity mapping otherwise). Resolved by the platform
        # assembly, which knows the shard layout.
        self._queue_names = queue_names or (lambda path: [path])
        self.specs: dict[str, PipelineSpec] = {}       # by pipeline name
        self._by_entry: dict[str, PipelineSpec] = {}   # by entry path
        self._runs: dict[str, "_PipelineRun"] = {}     # by root task id
        self._loops: list[asyncio.Task] = []
        self._stop = asyncio.Event()
        self._started = False
        self._runs_total = self.metrics.counter(
            "ai4e_pipeline_runs_total",
            "Pipeline runs reaching a terminal outcome, by pipeline")
        self._stages_total = self.metrics.counter(
            "ai4e_pipeline_stages_total",
            "Pipeline stage transitions, by pipeline/stage/outcome "
            "(completed/failed/expired/shed, plus cached stage-cache "
            "hits and resumed replays that skipped execution)")
        self._ttfp = self.metrics.histogram(
            "ai4e_pipeline_ttfp_seconds",
            "Time from run launch to the first stage partial, by pipeline")
        # Sub-task terminal transitions arrive on the store's listener
        # thread; runs are driven on the coordinator's event loop.
        store.add_listener(self._on_task_change)
        self._loop: asyncio.AbstractEventLoop | None = None

    # -- registration --------------------------------------------------------

    def register(self, spec: PipelineSpec) -> None:
        if spec.name in self.specs:
            raise ValueError(f"pipeline {spec.name!r} already registered")
        self.specs[spec.name] = spec
        self._by_entry[spec.entry_path] = spec
        for qn in self._queue_names(spec.entry_path):
            self.broker.register_queue(qn)
        if self._started:
            # Late registration on a running platform: start its loops now.
            loop = asyncio.get_running_loop()
            for qn in self._queue_names(spec.entry_path):
                self._loops.append(loop.create_task(self._consume(spec, qn)))

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._stop.clear()
        self._started = True
        self._loop = asyncio.get_running_loop()
        for spec in self.specs.values():
            for qn in self._queue_names(spec.entry_path):
                self._loops.append(
                    self._loop.create_task(self._consume(spec, qn)))

    async def stop(self) -> None:
        self._started = False
        self._stop.set()
        for t in self._loops:
            t.cancel()
        for run in list(self._runs.values()):
            run.cancel()
        await asyncio.gather(*self._loops,
                             *(r.driver for r in self._runs.values()
                               if r.driver is not None),
                             return_exceptions=True)
        self._loops.clear()
        self._runs.clear()

    # -- entry-queue consumption --------------------------------------------

    async def _consume(self, spec: PipelineSpec, queue_name: str) -> None:
        """Pop root tasks off the entry queue and launch runs. The message
        is completed as soon as the run is adopted in memory: the run is
        event-driven from there, and a control-plane restart re-seeds the
        (still non-terminal) root task back onto this queue — which is the
        resume path, deliberately identical to first launch."""
        while not self._stop.is_set():
            msg = await self.broker.receive(queue_name, timeout=1.0)
            if msg is None:
                continue
            try:
                await self._adopt(spec, msg)
            except asyncio.CancelledError:
                self.broker.abandon(msg)
                raise
            except Exception:  # noqa: BLE001 — the consumer loop must never die
                log.exception("pipeline %s: adopting task %s crashed; "
                              "redelivering", spec.name, msg.task_id)
                self.broker.abandon(msg)

    async def _adopt(self, spec: PipelineSpec, msg) -> None:
        root_id = msg.task_id
        if root_id in self._runs:
            self.broker.complete(msg)  # duplicate delivery of a live run
            return
        try:
            record = self.store.get(root_id)
        except TaskNotFound:
            self.broker.complete(msg)  # evicted (tight retention)
            return
        if record.canonical_status in TaskStatus.TERMINAL:
            self.broker.complete(msg)  # redelivery of a finished run
            return
        self.broker.complete(msg)
        if self.hub is not None:
            # Buffer the run's events even with no subscriber yet — a
            # client attaching after stage 1 completed must still see
            # its partial (the replay window).
            self.hub.track(root_id)
        run = _PipelineRun(self, spec, record)
        self._runs[root_id] = run
        run.driver = asyncio.get_running_loop().create_task(run.drive())
        run.driver.add_done_callback(lambda _t: self._runs.pop(root_id, None))

    # -- store feed ----------------------------------------------------------

    def _on_task_change(self, task) -> None:
        """Store listener (any thread): route stage sub-task terminal
        transitions to their run's event queue on the coordinator loop."""
        status = task.canonical_status
        if status not in TaskStatus.TERMINAL:
            return
        parsed = split_sub_task_id(task.task_id)
        if parsed is None:
            return
        root_id, stage = parsed
        run = self._runs.get(root_id)
        if run is None or self._loop is None:
            return
        try:
            self._loop.call_soon_threadsafe(run.note_stage_terminal, stage,
                                            status, task.status)
        except RuntimeError:
            pass  # loop closed mid-shutdown

    # -- shared helpers (used by runs) ---------------------------------------

    def stamp(self, task_id: str, event: str, reason: str) -> None:
        if self.observability is None:
            return
        self.observability.stamp(
            task_id, hop.ledger_event(event, "pipeline", reason=reason))

    def count_stage(self, spec: PipelineSpec, stage: str,
                    outcome: str) -> None:
        self._stages_total.inc(pipeline=spec.name, stage=stage,
                               outcome=outcome)

    def count_run(self, spec: PipelineSpec, outcome: str) -> None:
        self._runs_total.inc(pipeline=spec.name, outcome=outcome)

    def observe_ttfp(self, spec: PipelineSpec, seconds: float) -> None:
        self._ttfp.observe(seconds, pipeline=spec.name)


class _PipelineRun:
    """One root task's DAG execution (coordinator-loop only)."""

    def __init__(self, coord: PipelineCoordinator, spec: PipelineSpec,
                 record: APITask):
        self.coord = coord
        self.spec = spec
        self.root_id = record.task_id
        self.deadline_at = record.deadline_at
        self.priority = record.priority
        # Stage-cache participation: a cache-enabled gateway stamps a
        # CacheKey on every cacheable non-bypassed request — its absence
        # means the caller opted out (X-Cache-Bypass), and the documented
        # bypass contract ("no cache read, no store") must hold for the
        # run's STAGES too, not just the whole-request key.
        self.use_stage_cache = (coord.result_cache is not None
                                and bool(record.cache_key))
        self.states: dict[str, StageState] = initial_states(spec)
        self.events: asyncio.Queue = asyncio.Queue()
        self.driver: asyncio.Task | None = None
        self.launched_at = time.time()
        self._first_partial_at = 0.0

    # -- event intake (called via call_soon_threadsafe) ----------------------

    def note_stage_terminal(self, stage: str, canonical: str,
                            prose: str) -> None:
        self.events.put_nowait(("stage", stage, canonical, prose))

    def cancel(self) -> None:
        if self.driver is not None:
            self.driver.cancel()

    # -- drive ---------------------------------------------------------------

    async def drive(self) -> None:
        try:
            await self._update_root(
                f"running - pipeline {self.spec.name}", TaskStatus.RUNNING)
            self._resume_completed_stages()
            await self._dispatch_ready()
            while not self._all_resolved():
                try:
                    kind, stage, canonical, prose = await asyncio.wait_for(
                        self.events.get(),
                        timeout=self.spec.rescan_interval)
                except asyncio.TimeoutError:
                    # Safety rescan: a listener wakeup lost across a shard
                    # failover must not wedge the run — re-read every
                    # in-flight stage's sub-record from the store.
                    self._rescan()
                    await self._dispatch_ready()
                    continue
                if kind == "stage":
                    await self._on_stage_terminal(stage, canonical, prose)
                    await self._dispatch_ready()
            await self._finish()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a run crash must fail the root loudly
            log.exception("pipeline %s run %s crashed", self.spec.name,
                          self.root_id)
            try:
                if not self._root_terminal():
                    await self._update_root(
                        f"failed - pipeline {self.spec.name} coordinator "
                        "error", TaskStatus.FAILED)
                    self.coord.count_run(self.spec, "failed")
            except Exception:  # noqa: BLE001
                log.exception("could not fail pipeline run %s", self.root_id)

    # -- stage scheduling ----------------------------------------------------

    def _resume_completed_stages(self) -> None:
        """A relaunched run (restart re-seed, redelivered root) adopts any
        stage whose result already landed under the root's stage key —
        completed work is never re-executed across a crash."""
        for name, st in self.states.items():
            if self.coord.store.get_result(self.root_id, stage=name) is not None:
                st.status = "completed"
                st.resumed = True
                st.finished_at = time.time()
                self.coord.count_stage(self.spec, name, "resumed")

    def _ready_stages(self) -> list[StageState]:
        out = []
        for name in self.spec.order:
            st = self.states[name]
            if st.status != "pending":
                continue
            deps = [self.states[d] for d in st.spec.after]
            if any(not d.terminal for d in deps):
                continue
            out.append(st)
        return out

    async def _dispatch_ready(self) -> None:
        """Offer every ready stage once per pass; cache-satisfied stages
        resolve synchronously, so the pass loops until no new stage became
        ready (a fully-cached re-run completes in ONE pass, no broker
        round trips at all). Brownout-delayed stages stay pending but are
        offered at most once per pass (their timer re-enters the loop)."""
        if self._root_terminal():
            return
        offered: set[str] = set()
        progressed = True
        while progressed and not self._root_terminal():
            progressed = False
            for st in self._ready_stages():
                if st.spec.name in offered:
                    continue
                offered.add(st.spec.name)
                if not await self._launch_stage(st):
                    return  # run reached a terminal outcome mid-dispatch
                progressed = True

    async def _launch_stage(self, st: StageState) -> bool:
        """Dispatch one ready stage (or satisfy it from cache/quorum
        bookkeeping). Returns False when the RUN terminated instead."""
        spec, name = self.spec, st.spec.name
        successes = [d for d in st.spec.after
                     if self.states[d].status == "completed"]
        if len(successes) < st.spec.required_successes():
            # Join barrier unsatisfiable: more branches failed than the
            # declared quorum tolerates.
            failed = [d for d in st.spec.after
                      if self.states[d].status != "completed"]
            st.status = "failed"
            st.detail = (f"quorum {st.spec.required_successes()}/"
                         f"{len(st.spec.after)} unsatisfied "
                         f"(failed branches: {', '.join(failed)})")
            st.finished_at = time.time()
            self.coord.count_stage(spec, name, "failed")
            self._publish_stage_event(st)
            await self._fail_run(f"stage {name}: {st.detail}")
            return False

        join = self._stage_input(st.spec, successes)

        # Stage-budget admission check at the transition: a stage whose
        # carved window (or the whole request) is already spent sheds HERE
        # — before any broker message exists (the ISSUE's "a dead stage
        # sheds before dispatch").
        deadline = stage_deadline(st.spec, self.deadline_at)
        now = time.time()
        if deadline and now >= deadline:
            st.status = "expired"
            st.detail = "stage budget spent before dispatch"
            st.finished_at = now
            self.coord.count_stage(spec, name, "expired")
            if self.coord.admission is not None:
                self.coord.admission.note_expired(
                    "pipeline", self._stage_priority(st.spec))
            self.coord.stamp(self.root_id, hop.EXPIRED,
                             f"stage {name} pre-dispatch")
            self._publish_stage_event(st)
            await self._expire_run(f"stage {name} budget spent")
            return False

        # Brownout per stage class (orchestration ladder via admission):
        # a degraded mode refusing this stage's class delays the dispatch
        # instead of burning backend capacity the ladder just shed — the
        # stage's own deadline bounds the wait.
        adm = self.coord.admission
        if adm is not None:
            brown = adm.brownout_refusal(self._stage_priority(st.spec))
            if brown is not None:
                retry_after, _mode = brown
                adm.note_shed("pipeline", self._stage_priority(st.spec))
                self.coord.count_stage(spec, name, "shed")
                self.coord.stamp(self.root_id, hop.SHED,
                                 f"stage {name} brownout")
                wait = min(max(0.05, retry_after),
                           max(0.05, (deadline - now)
                               if deadline else retry_after))
                self._arm_retry(name, wait)
                return True

        # Stage result cache (rescache/): family = the stage endpoint's
        # path (the same namespace a worker checkpoint reload already
        # invalidates), extra = the pipeline/stage qualifier so two
        # pipelines sharing a backend never share entries by accident.
        cache = (self.coord.result_cache
                 if st.spec.cacheable and self.use_stage_cache else None)
        key = ""
        if cache is not None:
            key = request_key(st.spec.endpoint_path, join.body,
                              join.content_type,
                              extra=f"pipeline={spec.name}/{name}")
            found = cache.get(key, count=False)
            if found is not None:
                payload, ctype = found
                st.status = "completed"
                st.cached = True
                st.finished_at = time.time()
                self._record_stage_result(name, payload, ctype)
                self.coord.count_stage(spec, name, "cached")
                self.coord.stamp(self.root_id, hop.STAGE,
                                 f"{name} cached")
                self._note_partial(st)
                self._publish_stage_event(st, result=(payload, ctype))
                return True
        st.cache_key = key  # remembered for the fill on completion

        sub_id = sub_task_id(self.root_id, name)
        try:
            existing = self.coord.store.get(sub_id)
        except TaskNotFound:
            existing = None
        if existing is not None:
            canonical = existing.canonical_status
            if canonical == TaskStatus.COMPLETED:
                # Resume: the stage finished before the crash but its
                # result never got copied onto the root — adopt it now.
                found = self.coord.store.get_result(sub_id)
                if found is not None:
                    st.status = "completed"
                    st.resumed = True
                    st.finished_at = time.time()
                    self._record_stage_result(name, found[0], found[1])
                    self.coord.count_stage(spec, name, "resumed")
                    self._note_partial(st)
                    self._publish_stage_event(st, result=found)
                    return True
                # Completed with no retrievable result (evicted sub-record
                # payload): fall through and re-dispatch.
            elif canonical not in TaskStatus.TERMINAL:
                # Resume: the sub-task (and its broker message, re-seeded
                # by the restart) is already in flight — just wait for it.
                st.status = "dispatched"
                st.dispatched_at = time.time()
                return True
            # failed/expired predecessor: re-dispatch below is the retry —
            # the same created-rewrite the redrive surface performs.
        self.coord.store.upsert(APITask(
            task_id=sub_id,
            endpoint=st.spec.endpoint,
            body=join.body,
            content_type=join.content_type,
            status=TaskStatus.CREATED,
            backend_status=TaskStatus.CREATED,
            publish=True,
            deadline_at=deadline,
            priority=self._stage_priority(st.spec),
        ))
        st.status = "dispatched"
        st.dispatched_at = time.time()
        self.coord.count_stage(spec, name, "dispatched")
        self.coord.stamp(self.root_id, hop.STAGE, f"{name} dispatched")
        self._publish_stage_event(st)
        return True

    def _arm_retry(self, stage: str, wait: float) -> None:
        """Re-offer a brownout-delayed stage to the scheduler after
        ``wait`` seconds (driver-loop timer; the event re-enters the
        ordinary dispatch path, deadline re-checked there)."""
        loop = asyncio.get_running_loop()

        def fire() -> None:
            self.events.put_nowait(("stage", "", "", ""))  # wake + rescan

        loop.call_later(wait, fire)

    def _stage_priority(self, stage_spec) -> int:
        return (stage_spec.priority if stage_spec.priority is not None
                else self.priority)

    # -- stage completion ----------------------------------------------------

    async def _on_stage_terminal(self, stage: str, canonical: str,
                                 prose: str) -> None:
        if not stage:
            return  # timer wakeup (_arm_retry)
        st = self.states.get(stage)
        if st is None or st.status != "dispatched":
            return  # late duplicate of an already-resolved stage
        if canonical == TaskStatus.COMPLETED:
            sub_id = sub_task_id(self.root_id, stage)
            found = self.coord.store.get_result(sub_id)
            if found is not None:
                payload, ctype = found
                st.status = "completed"
                st.finished_at = time.time()
                self._record_stage_result(stage, payload, ctype)
                cache = (self.coord.result_cache
                         if st.spec.cacheable and self.use_stage_cache
                         else None)
                if cache is not None and st.cache_key:
                    cache.put(st.cache_key, payload, ctype)
                self.coord.count_stage(self.spec, stage, "completed")
                self.coord.stamp(self.root_id, hop.STAGE,
                                 f"{stage} completed")
                self._note_partial(st)
                self._publish_stage_event(st, result=(payload, ctype))
                return
            # Completed WITHOUT a retrievable result (worker stored
            # nothing, or eviction raced the completion): fabricating an
            # empty payload would feed downstream stages garbage and
            # "complete" the run with a hollow answer — treat the branch
            # as failed (quorum may still tolerate it) via the shared
            # failure path below.
            canonical = TaskStatus.FAILED
            prose = "completed without a retrievable result"
        st.status = ("expired" if canonical == TaskStatus.EXPIRED
                     else "failed")
        st.detail = prose
        st.finished_at = time.time()
        self.coord.count_stage(self.spec, stage, st.status)
        self.coord.stamp(self.root_id, hop.STAGE, f"{stage} {st.status}")
        self._publish_stage_event(st)
        if not self._failure_tolerated(stage):
            if st.status == "expired":
                await self._expire_run(f"stage {stage} deadline")
            else:
                await self._fail_run(f"stage {stage}: {prose}")

    def _failure_tolerated(self, stage: str) -> bool:
        """A failed branch is tolerable iff every downstream join can still
        reach its quorum — and the stage feeds at least one downstream
        (a failed sink always fails the run)."""
        downstream = self.spec.downstream_of(stage)
        if not downstream:
            return False
        for name in downstream:
            st = self.states[name]
            possible = sum(
                1 for d in st.spec.after
                if self.states[d].status in ("pending", "dispatched",
                                             "completed"))
            if possible < st.spec.required_successes():
                return False
        return True

    def _rescan(self) -> None:
        """Re-read in-flight stages' sub-records — the lost-wakeup net."""
        for name, st in self.states.items():
            if st.status != "dispatched":
                continue
            try:
                record = self.coord.store.get(
                    sub_task_id(self.root_id, name))
            except TaskNotFound:
                continue
            canonical = record.canonical_status
            if canonical in TaskStatus.TERMINAL:
                self.events.put_nowait(("stage", name, canonical,
                                        record.status))

    # -- run terminal outcomes ----------------------------------------------

    def _all_resolved(self) -> bool:
        if self._root_terminal():
            return True
        return all(st.terminal for st in self.states.values())

    async def _finish(self) -> None:
        if self._root_terminal():
            return  # already failed/expired mid-run
        failed = [n for n, st in self.states.items()
                  if st.status in ("failed", "expired")]
        sinks = self.spec.sinks()
        sink_ok = [n for n in sinks
                   if self.states[n].status == "completed"]
        if not sink_ok:
            await self._fail_run(
                f"no sink stage completed (failed: {', '.join(failed)})")
            return
        # Final result: a single sink's payload verbatim; multiple sinks
        # (or a sink quorum with failures) produce a join document.
        if len(sinks) == 1:
            found = self.coord.store.get_result(self.root_id,
                                                stage=sinks[0])
            if found is not None:
                self._set_root_result(found[0], found[1])
        else:
            doc = self._sink_document(sink_ok)
            self._set_root_result(
                json.dumps(doc, separators=(",", ":")).encode(),
                "application/json")
        stages_run = sum(1 for st in self.states.values()
                         if st.status == "completed" and not st.cached
                         and not st.resumed)
        cached = sum(1 for st in self.states.values() if st.cached)
        summary = (f"completed - pipeline {self.spec.name} "
                   f"({stages_run} executed, {cached} cached"
                   + (f", {len(failed)} tolerated" if failed else "") + ")")
        await self._update_root(summary, TaskStatus.COMPLETED)
        self.coord.count_run(self.spec, "completed")

    async def _fail_run(self, why: str) -> None:
        if self._root_terminal():
            return
        await self._update_root(
            f"failed - pipeline {self.spec.name}: {why}", TaskStatus.FAILED)
        self.coord.count_run(self.spec, "failed")

    async def _expire_run(self, why: str) -> None:
        if self._root_terminal():
            return
        await self._update_root(
            f"expired - pipeline {self.spec.name}: {why} (pipeline)",
            TaskStatus.EXPIRED)
        self.coord.count_run(self.spec, "expired")

    def _root_terminal(self) -> bool:
        try:
            record = self.coord.store.get(self.root_id)
        except TaskNotFound:
            return True  # evicted — nothing left to drive
        return record.canonical_status in TaskStatus.TERMINAL

    async def _update_root(self, status: str, backend_status: str) -> None:
        """Conditional root transition (AIL003): the reaper's
        running-timeout rescue or the entry-queue dead-letter handler can
        race a terminal outcome onto the root from another thread — so
        the write is the store's ATOMIC compare-and-transition, keyed on
        the only two live states a pipeline root occupies (``created``
        fresh/re-adopted, ``running`` mid-run). Both misses mean the root
        is already terminal (or evicted): this run's outcome is dropped,
        never clobbered over one the client may have read."""
        try:
            for expected in (TaskStatus.RUNNING, TaskStatus.CREATED):
                if self.coord.store.update_status_if(
                        self.root_id, expected, status,
                        backend_status) is not None:
                    return
        except TaskNotFound:
            pass  # evicted mid-run (tight retention)

    # -- results + events ----------------------------------------------------

    def _record_stage_result(self, stage: str, payload: bytes,
                             ctype: str) -> None:
        try:
            self.coord.store.set_result(self.root_id, payload,
                                        content_type=ctype, stage=stage)
        except TaskNotFound:
            pass  # root evicted; the run is about to notice

    def _set_root_result(self, payload: bytes, ctype: str) -> None:
        try:
            self.coord.store.set_result(self.root_id, payload,
                                        content_type=ctype)
        except TaskNotFound:
            pass

    def _note_partial(self, st: StageState) -> None:
        if self._first_partial_at:
            return
        self._first_partial_at = time.time()
        self.coord.observe_ttfp(self.spec,
                                self._first_partial_at - self.launched_at)

    def _publish_stage_event(self, st: StageState,
                             result: tuple[bytes, str] | None = None) -> None:
        hub = self.coord.hub
        if hub is None:
            return
        data: dict = {"pipeline": self.spec.name, "stage": st.spec.name,
                      "state": ("cached" if st.cached else st.status)}
        if st.detail:
            data["detail"] = st.detail
        if result is not None:
            payload, ctype = result
            data["resultAvailable"] = True
            data["contentType"] = ctype
            if len(payload) <= INLINE_RESULT_BYTES:
                if ctype == "application/json":
                    try:
                        data["result"] = json.loads(payload.decode("utf-8"))
                    except (ValueError, UnicodeDecodeError):
                        data["resultB64"] = base64.b64encode(
                            payload).decode("ascii")
                else:
                    data["resultB64"] = base64.b64encode(
                        payload).decode("ascii")
        hub.publish(self.root_id, STAGE, data)

    def _sink_document(self, sink_ok: list[str]) -> dict:
        """Final answer for a multi-sink DAG: one JSON document over the
        completed sinks (same encoding rules as the fan-in join doc)."""
        stages_doc: dict = {}
        for name in sink_ok:
            found = self.coord.store.get_result(self.root_id, stage=name)
            if found is None:
                continue
            payload, ctype = found
            if ctype == "application/json":
                try:
                    stages_doc[name] = json.loads(payload.decode("utf-8"))
                    continue
                except (ValueError, UnicodeDecodeError):
                    pass
            stages_doc[name] = {"b64": base64.b64encode(payload).decode(),
                                "contentType": ctype}
        return {"pipeline": self.spec.name, "stages": stages_doc}

    # -- stage input composition --------------------------------------------

    def _stage_input(self, stage_spec, successes: list[str]) -> JoinInput:
        store = self.coord.store
        if stage_spec.input == "original" or not stage_spec.after:
            body = store.get_original_body(self.root_id)
            try:
                record = store.get(self.root_id)
                ctype = record.content_type
            except TaskNotFound:
                ctype = "application/octet-stream"
            return JoinInput(body=body, content_type=ctype,
                             arrived=tuple(successes))
        if len(stage_spec.after) == 1:
            found = store.get_result(self.root_id, stage=stage_spec.after[0])
            if found is None:
                return JoinInput(arrived=(), missing=stage_spec.after)
            return JoinInput(body=found[0], content_type=found[1],
                             arrived=tuple(successes))
        # Fan-in: a JSON join document over every arrived branch. JSON
        # branch results inline; binary ones ride base64 so the document
        # is always valid JSON.
        stages_doc: dict = {}
        for dep in successes:
            found = store.get_result(self.root_id, stage=dep)
            if found is None:
                continue
            payload, ctype = found
            if ctype == "application/json":
                try:
                    stages_doc[dep] = json.loads(payload.decode("utf-8"))
                    continue
                except (ValueError, UnicodeDecodeError):
                    pass
            stages_doc[dep] = {"b64": base64.b64encode(payload).decode(),
                               "contentType": ctype}
        missing = tuple(d for d in stage_spec.after if d not in successes)
        doc = {"pipeline": self.spec.name, "stages": stages_doc,
               "arrived": sorted(stages_doc), "missing": list(missing)}
        return JoinInput(
            body=json.dumps(doc, separators=(",", ":")).encode(),
            content_type="application/json",
            arrived=tuple(successes), missing=missing)
