"""Declared pipeline DAGs — the spec the coordinator executes.

The platform's composition story so far is *emergent*: a stage finishes,
rewrites the task to ``created`` with the next endpoint, and republishes
(``service/task_manager.add_pipeline_task`` — the reference's
``distributed_api_task.py:67-100`` ensembles). That shape cannot express
fan-out, cannot carve a per-stage budget from the request's deadline, and
gives the platform no plan to resume from. A ``PipelineSpec`` is the same
composition *declared*: named stages, explicit edges, fan-in joins with a
failure-tolerance quorum, and per-stage deadline fractions — validated once
at registration, executed by ``coordinator.PipelineCoordinator`` under ONE
client-visible TaskId (docs/pipelines.md).

Stage sub-task naming: each stage of a run executes as a store sub-record
``{root_task_id}~{stage_name}`` — ``~`` never appears in platform-minted
GUIDs and stage names exclude it by validation, so the root id is always
recoverable with one ``rpartition``.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass

# SUB_TASK_SEP re-exported from the task module — it lives beside the
# ':' result-stage separator it complements, and the HTTP store surface
# enforces it (forged sub-record creates are refused there): '~' is
# valid in URLs, absent from GUIDs, and excluded from stage names below.
from ..taskstore.task import SUB_TASK_SEP, endpoint_path

_STAGE_NAME_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


def sub_task_id(root_task_id: str, stage: str) -> str:
    return f"{root_task_id}{SUB_TASK_SEP}{stage}"


def split_sub_task_id(task_id: str) -> tuple[str, str] | None:
    """``(root, stage)`` when ``task_id`` is a stage sub-task id, else None."""
    root, sep, stage = task_id.rpartition(SUB_TASK_SEP)
    if not sep or not root or not stage:
        return None
    return root, stage


class PipelineSpecError(ValueError):
    """The spec is not a well-formed DAG (raised at registration, never at
    request time — a bad spec must fail the deployment, not a task)."""


@dataclass(frozen=True)
class StageSpec:
    """One node of the DAG.

    - ``name``: stage id (``[A-Za-z0-9_-]``; also the store's result-stage
      key under the root TaskId, and the hop-ledger/metric label);
    - ``endpoint``: the backend URI (or bare path) the stage's sub-task is
      dispatched to — a route the platform has a dispatcher for
      (``register_internal_route`` or a published API);
    - ``after``: upstream stage names (empty = an entry stage fed by the
      client's original body);
    - ``deadline_fraction``: share of the request's REMAINING deadline
      budget this stage may spend, carved at dispatch time from the
      ``X-Deadline-Ms`` the admission layer anchored (0 = no carve — the
      stage inherits the root deadline whole);
    - ``quorum``: fan-in tolerance — minimum number of upstream stages
      that must SUCCEED for this stage to run (0 = all of ``after``);
      failed branches below the quorum bar are recorded in the join
      input, not fatal;
    - ``input``: what the stage's sub-task body carries — ``"auto"``
      (original body for entry stages; the single upstream's result; a
      JSON join document for fan-in) or ``"original"`` (always replay the
      client's original body, the reference's ensemble semantics);
    - ``priority``: admission class override for this stage's sub-task
      (None = inherit the request's class) — the degradation ladder's
      brownout applies per stage class;
    - ``cacheable``: whether the stage participates in the stage result
      cache (``rescache/`` — key = stage endpoint family + canonical
      stage input hash, so a re-run or resumed pipeline skips completed
      stages).
    """

    name: str
    endpoint: str
    after: tuple[str, ...] = ()
    deadline_fraction: float = 0.0
    quorum: int = 0
    input: str = "auto"
    priority: int | None = None
    cacheable: bool = True

    def __post_init__(self):
        # dataclass(frozen) + normalization: tolerate lists in user specs.
        object.__setattr__(self, "after", tuple(self.after))

    @property
    def endpoint_path(self) -> str:
        return endpoint_path(self.endpoint)

    def required_successes(self) -> int:
        """Upstream successes this stage needs before it may run."""
        if not self.after:
            return 0
        return self.quorum if self.quorum > 0 else len(self.after)


@dataclass(frozen=True)
class PipelineSpec:
    """A validated DAG of stages published as one async API.

    ``prefix`` is the public gateway route clients POST; ``stages`` the
    nodes. Validation (at construction) guarantees: unique well-formed
    stage names, known edges, acyclicity, sane quorums, and that no
    root→sink path's deadline fractions exceed 1.0 — so budget carving
    can never promise a stage time the request does not have.
    """

    name: str
    prefix: str
    stages: tuple[StageSpec, ...] = ()
    # Maximum seconds a run may sit waiting on sub-task events before the
    # coordinator re-reads their records from the store — the safety net
    # against a lost listener wakeup (never the primary signal).
    rescan_interval: float = 15.0

    def __post_init__(self):
        object.__setattr__(self, "stages", tuple(self.stages))
        if not _STAGE_NAME_RE.match(self.name or ""):
            raise PipelineSpecError(
                f"pipeline name {self.name!r} must match "
                f"{_STAGE_NAME_RE.pattern}")
        if not self.stages:
            raise PipelineSpecError(f"pipeline {self.name!r} has no stages")
        names = [s.name for s in self.stages]
        if len(set(names)) != len(names):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise PipelineSpecError(
                f"pipeline {self.name!r}: duplicate stage name(s) {dupes}")
        by_name = {s.name: s for s in self.stages}
        for s in self.stages:
            if not _STAGE_NAME_RE.match(s.name):
                raise PipelineSpecError(
                    f"stage name {s.name!r} must match "
                    f"{_STAGE_NAME_RE.pattern} (it is a result-stage key "
                    f"and a sub-task id component)")
            if not s.endpoint:
                raise PipelineSpecError(f"stage {s.name!r} has no endpoint")
            for dep in s.after:
                if dep not in by_name:
                    raise PipelineSpecError(
                        f"stage {s.name!r} depends on unknown stage {dep!r}")
                if dep == s.name:
                    raise PipelineSpecError(
                        f"stage {s.name!r} depends on itself")
            if s.quorum < 0 or s.quorum > len(s.after):
                raise PipelineSpecError(
                    f"stage {s.name!r}: quorum {s.quorum} out of range for "
                    f"{len(s.after)} upstream stage(s)")
            if not 0.0 <= s.deadline_fraction <= 1.0:
                raise PipelineSpecError(
                    f"stage {s.name!r}: deadline_fraction "
                    f"{s.deadline_fraction} outside [0, 1]")
            if s.input not in ("auto", "original"):
                raise PipelineSpecError(
                    f"stage {s.name!r}: input must be 'auto' or 'original', "
                    f"got {s.input!r}")
        order = self._topo_order(by_name)
        object.__setattr__(self, "_order", order)
        # Budget sanity: along every path the carved fractions must fit in
        # one request budget. path_sum(s) = fraction(s) + max over deps.
        path_sum: dict[str, float] = {}
        for name in order:
            s = by_name[name]
            upstream = max((path_sum[d] for d in s.after), default=0.0)
            path_sum[name] = upstream + s.deadline_fraction
            if path_sum[name] > 1.0 + 1e-9:
                raise PipelineSpecError(
                    f"stage {s.name!r}: cumulative deadline fractions along "
                    f"its path reach {path_sum[name]:.3f} > 1.0 — the DAG "
                    f"would promise stages more budget than the request has")

    def _topo_order(self, by_name: dict[str, StageSpec]) -> tuple[str, ...]:
        """Deterministic topological order; raises on cycles."""
        state: dict[str, int] = {}  # 0 visiting / 1 done
        order: list[str] = []

        def visit(name: str, trail: tuple[str, ...]) -> None:
            mark = state.get(name)
            if mark == 1:
                return
            if mark == 0:
                cycle = " -> ".join((*trail[trail.index(name):], name))
                raise PipelineSpecError(
                    f"pipeline {self.name!r} has a cycle: {cycle}")
            state[name] = 0
            for dep in by_name[name].after:
                visit(dep, (*trail, name))
            state[name] = 1
            order.append(name)

        for s in self.stages:
            visit(s.name, ())
        return tuple(order)

    # -- derived views -------------------------------------------------------

    @property
    def order(self) -> tuple[str, ...]:
        """Stage names in topological order (dependencies first)."""
        return self._order  # set in __post_init__

    def stage(self, name: str) -> StageSpec:
        for s in self.stages:
            if s.name == name:
                return s
        raise KeyError(name)

    def downstream_of(self, name: str) -> tuple[str, ...]:
        return tuple(s.name for s in self.stages if name in s.after)

    def sinks(self) -> tuple[str, ...]:
        """Stages nothing depends on — their results form the final answer
        (a single sink's result verbatim; a JSON join document otherwise)."""
        have_downstream = {d for s in self.stages for d in s.after}
        return tuple(s.name for s in self.stages
                     if s.name not in have_downstream)

    @property
    def entry_path(self) -> str:
        """The internal endpoint path root tasks are published under — the
        coordinator's queue. Distinct namespace from any backend route so a
        root task can never be mistaken for dispatchable stage work."""
        return f"/v1/_pipelines/{self.name}"


def stage_deadline(stage: StageSpec, root_deadline_at: float,
                   now: float | None = None) -> float:
    """The absolute deadline a stage's sub-task carries: its declared
    fraction of the request's REMAINING budget, carved at dispatch time —
    never later than the root deadline (transport time already spent can
    only shrink a stage's window, exactly like every other hop's deadline
    propagation, ``admission/deadline.py``). 0.0 (no deadline) when the
    request carried none."""
    if not root_deadline_at:
        return 0.0
    if not stage.deadline_fraction:
        return root_deadline_at
    now = time.time() if now is None else now
    remaining = root_deadline_at - now
    if remaining <= 0:
        return root_deadline_at
    return min(root_deadline_at, now + remaining * stage.deadline_fraction)


@dataclass
class StageState:
    """Mutable per-run bookkeeping for one stage (coordinator-internal)."""

    spec: StageSpec
    status: str = "pending"   # pending|dispatched|completed|failed|expired
    cached: bool = False      # satisfied by the stage result cache
    resumed: bool = False     # satisfied by a pre-existing stage result
    dispatched_at: float = 0.0
    finished_at: float = 0.0
    detail: str = ""          # failure/shed prose for events + final status
    cache_key: str = ""       # stage-cache key captured at dispatch

    @property
    def terminal(self) -> bool:
        return self.status in ("completed", "failed", "expired")


def initial_states(spec: PipelineSpec) -> dict[str, StageState]:
    return {s.name: StageState(spec=s) for s in spec.stages}


@dataclass
class JoinInput:
    """Composed input for a stage with upstream dependencies."""

    body: bytes = b""
    content_type: str = "application/json"
    # Which upstream results fed the body (successes) / were tolerated
    # (failures below the quorum bar) — surfaced in events and the join doc.
    arrived: tuple[str, ...] = ()
    missing: tuple[str, ...] = ()
