"""Per-task event hub — the streaming surface's fan-out core.

The long-poll contract (``GET /task/{id}?wait=``) answers exactly once,
with the terminal record. Pipelines produce *partial* results worth
delivering earlier — a stage's output is useful the moment the stage
finishes, and a token-producing stage can emit incremental chunks — so
the hub turns the task lifecycle into an ordered event stream:

- producers (``coordinator.PipelineCoordinator``, the store's change
  feed, token-streaming workers via the HTTP event surface) ``publish``
  typed events under a TaskId from any thread;
- consumers (the gateway's SSE handler, ``GET
  /v1/taskmanagement/task/{id}/events``) ``subscribe`` and receive the
  task's buffered history *then* live events, in publish order, ending
  at the ``terminal`` event.

The attach-vs-event race is closed the same way the shard change feed
closes it (``taskstore/feed.py``): a bounded per-task replay buffer is
written and the waiter set collected under ONE lock, so an event is
either replayed at attach or delivered live — never neither. Event
history is observability state, not durable truth: bounded per task and
across tasks (LRU), dropped on eviction, gone on restart.

Event vocabulary (docs/pipelines.md keeps the client table):

- ``status``   — root task status transition ({"Status", "BackendStatus"});
- ``stage``    — pipeline stage transition ({"stage", "state":
  dispatched|completed|cached|failed|expired, "resultAvailable",
  "result"? (inline when small), "detail"?});
- ``chunk``    — incremental partial output from a token-producing stage
  ({"stage", "index", "data"});
- ``terminal`` — the task's terminal record; closes every stream.
"""

from __future__ import annotations

import asyncio
import json
import threading
from collections import OrderedDict

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..taskstore import TaskStatus

# Inline-result bound for stage events: a stage result at or under this
# size rides in the event itself; larger ones are announced
# (resultAvailable) and fetched via GET /v1/taskstore/result?stage=.
INLINE_RESULT_BYTES = 64 * 1024

TERMINAL = "terminal"
STATUS = "status"
STAGE = "stage"
CHUNK = "chunk"


def sse_encode(event: dict) -> bytes:
    """One event in Server-Sent-Events wire format (``id``/``event``/
    ``data`` fields; data is a single JSON line, so no multi-line
    framing is ever needed)."""
    data = json.dumps(event.get("data", {}), separators=(",", ":"))
    return (f"id: {event.get('seq', 0)}\n"
            f"event: {event.get('event', 'message')}\n"
            f"data: {data}\n\n").encode("utf-8")


#: The synthetic event type a subscriber sees in place of chunk history
#: the bounded replay dropped (never published by producers; minted at
#: attach time from the drop accounting).
TRUNCATED = "truncated"


class TaskEventHub:
    """Bounded, thread-safe per-task event fan-out with replay.

    Chunk hardening (docs/streaming.md): CHUNK events — per-token
    partials, potentially hundreds per task — are bounded separately
    from the first-``replay`` buffer the other event types keep. The
    newest ``chunk_replay`` chunks are retained (a tail ring: a client
    attaching mid-stream wants the RECENT tokens), older ones are
    dropped, and a subscriber whose attach point falls inside the
    dropped range receives one synthetic ``truncated`` event carrying
    the cumulative drop count — a slow client can never hold unbounded
    token history. ``subscribe``/``replay`` take ``after_seq`` (the SSE
    ``Last-Event-ID`` resume contract): replay starts strictly after it.
    """

    def __init__(self, replay: int = 256, chunk_replay: int = 128,
                 max_tasks: int = 4096,
                 metrics: MetricsRegistry | None = None):
        self._replay_cap = replay
        self._chunk_cap = chunk_replay
        self._max_tasks = max_tasks
        self._lock = threading.Lock()
        # task_id -> {"seq": int, "events": [event dicts], "done": bool}
        # LRU-ordered; oldest tracked task evicted past max_tasks.
        self._tasks: "OrderedDict[str, dict]" = OrderedDict()
        # task_id -> frozenset[(loop, asyncio.Queue)] — copy-on-write like
        # the gateway's waiter map: publish iterates from any thread while
        # subscribers attach/detach on their loops.
        self._subscribers: dict[str, frozenset] = {}
        metrics = metrics or DEFAULT_REGISTRY
        self._published = metrics.counter(
            "ai4e_task_events_total",
            "Task events published to the streaming hub, by type")

    # -- producer side -------------------------------------------------------

    def track(self, task_id: str) -> None:
        """Start buffering events for a task even before any subscriber
        attaches (pipeline roots: a client that connects after stage 1
        completed must still see its partial)."""
        with self._lock:
            self._entry(task_id)

    def _entry(self, task_id: str) -> dict:
        entry = self._tasks.get(task_id)
        if entry is None:
            entry = self._tasks[task_id] = {
                "seq": 0, "events": [], "done": False,
                # Chunk-bound accounting: live chunk count in `events`,
                # cumulative dropped chunks, and the highest dropped seq
                # (the `truncated` marker's position at attach).
                "chunks": 0, "chunks_dropped": 0, "dropped_through": 0}
            while len(self._tasks) > self._max_tasks:
                self._tasks.popitem(last=False)
        else:
            self._tasks.move_to_end(task_id)
        return entry

    def publish(self, task_id: str, event_type: str, data: dict) -> None:
        """Append one event to the task's stream and wake subscribers.
        Thread-safe; events for tasks that are neither tracked nor
        subscribed are dropped (the hub must not grow with every task the
        platform ever serves)."""
        with self._lock:
            tracked = task_id in self._tasks
            has_subs = bool(self._subscribers.get(task_id))
            if not tracked and not has_subs:
                return
            entry = self._entry(task_id)
            if entry["done"]:
                return  # stream already closed by a terminal event
            entry["seq"] += 1
            event = {"seq": entry["seq"], "event": event_type, "data": data}
            if event_type == CHUNK:
                # Tail ring for token streams: keep the newest
                # chunk_replay chunks, evict the oldest past the cap —
                # the bounded-history contract (class docstring). The
                # scan for the oldest resident chunk starts at the last
                # eviction's index (everything before it is non-chunk
                # and a pop never moves those), so a long stream pays
                # O(chunk window), not O(buffer), per evicting publish.
                events = entry["events"]
                events.append(event)
                entry["chunks"] += 1
                if entry["chunks"] > self._chunk_cap:
                    floor = entry.get("chunk_floor", 0)
                    idx = next(i for i in range(floor, len(events))
                               if events[i]["event"] == CHUNK)
                    dropped = events.pop(idx)
                    entry["chunk_floor"] = idx
                    entry["chunks"] -= 1
                    entry["chunks_dropped"] += 1
                    entry["dropped_through"] = dropped["seq"]
            elif len(entry["events"]) < self._replay_cap:
                entry["events"].append(event)
            if event_type == TERMINAL:
                entry["done"] = True
            waiters = self._subscribers.get(task_id, frozenset())
        self._published.inc(type=event_type)
        for loop, queue in waiters:
            self._deliver(loop, queue, event)

    @staticmethod
    def _deliver(loop, queue, event) -> None:
        def put() -> None:
            # Runs ON the subscriber's loop (call_soon_threadsafe below),
            # so draining the queue here cannot race its consumer.
            try:
                queue.put_nowait(event)
            except asyncio.QueueFull:
                # Slow consumer: evict the OLDEST buffered event to admit
                # the newest — the terminal event (always last) is never
                # the one lost, and the seq numbering exposes the gap to
                # the consumer (ids skip).
                try:
                    queue.get_nowait()
                except asyncio.QueueEmpty:
                    pass
                try:
                    queue.put_nowait(event)
                except asyncio.QueueFull:
                    pass
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is running:
            put()
        else:
            try:
                loop.call_soon_threadsafe(put)
            except RuntimeError:
                pass  # subscriber's loop closed — it is gone

    # -- store feed ----------------------------------------------------------

    def attach_store(self, store) -> None:
        """Subscribe to the store's change feed: every transition of a
        tracked/subscribed task becomes a ``status`` event, and terminal
        transitions close the stream with ``terminal`` — so the streaming
        surface works for ANY task, with stage/chunk events layered on by
        the pipeline coordinator for DAG runs."""

        def on_task_change(task) -> None:
            status = task.canonical_status
            self.publish(task.task_id, STATUS,
                         {"Status": task.status,
                          "BackendStatus": task.backend_status})
            if status in TaskStatus.TERMINAL:
                self.publish(task.task_id, TERMINAL, task.to_dict())

        store.add_listener(on_task_change)

    # -- consumer side -------------------------------------------------------

    @staticmethod
    def _replay_view(entry: dict, after_seq: int) -> list[dict]:
        """The replay a subscriber resuming after ``after_seq`` sees:
        buffered events strictly past it, preceded by ONE synthetic
        ``truncated`` event when dropped chunk history falls inside the
        requested range. Caller holds the lock."""
        view = [e for e in entry["events"] if e["seq"] > after_seq]
        through = entry["dropped_through"]
        if through > after_seq:
            marker = {"seq": through, "event": TRUNCATED,
                      "data": {"dropped_chunks": entry["chunks_dropped"],
                               "through_seq": through}}
            at = next((i for i, e in enumerate(view)
                       if e["seq"] > through), len(view))
            view.insert(at, marker)
        return view

    def subscribe(self, task_id: str, after_seq: int = 0
                  ) -> "TaskEventStream":
        """Attach a consumer: returns an async-iterable stream yielding the
        task's replay buffer then live events, under one lock so no event
        can fall between replay and registration. ``after_seq`` is the
        ``Last-Event-ID`` resume point: replay starts strictly after it
        (0 = from the beginning)."""
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue(maxsize=1024)
        entry_key = (loop, queue)
        with self._lock:
            entry = self._entry(task_id)
            replay = self._replay_view(entry, after_seq)
            done = entry["done"]
            if not done:
                self._subscribers[task_id] = self._subscribers.get(
                    task_id, frozenset()) | {entry_key}
        return TaskEventStream(self, task_id, entry_key, replay, done,
                               seen_seq=after_seq)

    def _unsubscribe(self, task_id: str, entry_key) -> None:
        with self._lock:
            entries = self._subscribers.get(task_id)
            if not entries:
                return
            remaining = frozenset(e for e in entries if e is not entry_key)
            if remaining:
                self._subscribers[task_id] = remaining
            else:
                del self._subscribers[task_id]

    def replay(self, task_id: str, after_seq: int = 0) -> list[dict]:
        """The task's buffered events past ``after_seq``, with the same
        ``truncated`` marker a subscriber would see (introspection, and
        the gateway's already-terminal fast path)."""
        with self._lock:
            entry = self._tasks.get(task_id)
            return self._replay_view(entry, after_seq) if entry else []

    @property
    def subscriber_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._subscribers.values())


class TaskEventStream:
    """Async iterator over one task's events: replay first, then live.
    Ends after the ``terminal`` event; ``aclose`` (or exiting the
    iterator) detaches the subscription."""

    def __init__(self, hub: TaskEventHub, task_id: str, entry_key,
                 replay: list[dict], done: bool, seen_seq: int = 0):
        self._hub = hub
        self.task_id = task_id
        self._entry_key = entry_key
        self._pending = list(replay)
        self._queue = entry_key[1]
        self._live = not done
        # Resume point (Last-Event-ID): live events at or under it are
        # duplicates of what the client already consumed.
        self._seen_seq = seen_seq

    def __aiter__(self):
        return self

    async def __anext__(self) -> dict:
        event = await self.next_event(timeout=None)
        if event is None:
            raise StopAsyncIteration
        return event

    async def next_event(self, timeout: float | None) -> dict | None:
        """Next event, or None when the stream ended (terminal delivered)
        — raises ``asyncio.TimeoutError`` when ``timeout`` expires first."""
        while True:
            if self._pending:
                event = self._pending.pop(0)
            elif not self._live:
                await self.aclose()
                return None
            else:
                event = await asyncio.wait_for(self._queue.get(), timeout)
            if event["seq"] <= self._seen_seq:
                continue  # replay/live overlap: already delivered
            self._seen_seq = event["seq"]
            if event["event"] == TERMINAL:
                self._live = False
                await self.aclose()
            return event

    async def aclose(self) -> None:
        self._hub._unsubscribe(self.task_id, self._entry_key)
