"""YUV 4:2:0 host↔device wire codec — halve h2d bytes for image models.

On a remote-attached TPU the host→device link, not the chip, bounds image
throughput (measured ~20 MB/s through the axon tunnel: a 256×256×3 uint8
tile costs 196 608 bytes ⇒ ≤107 tiles/s no matter how fast the MXU is).
Camera/ aerial imagery arrives as JPEG, which already stores chroma
subsampled 4:2:0 — so shipping the device full-resolution chroma carries no
information the source had. This codec moves the subsampling boundary to the
host↔device link:

- host (``rgb_to_yuv420``): decoded RGB → planar JPEG-convention YCbCr with
  2×2-averaged chroma — 1.5 bytes/pixel, exactly half of raw RGB;
- device (``yuv420_to_rgb``): flat planes → nearest-upsampled chroma →
  inverse transform → normalized [0,1] float RGB, fused by XLA into the
  model's first convolution (one extra VMEM pass, zero extra HBM round
  trips).

The transform pair is JPEG's own (JFIF full-range BT.601), so accuracy
matches what the reference's JPEG-ingesting pipelines already see.
"""

from __future__ import annotations

import numpy as np


def yuv420_nbytes(h: int, w: int) -> int:
    return h * w + 2 * (h // 2) * (w // 2)


_native_encode = None
_native_tried = False


def _get_native_encode():
    """C++ encoder (``native/yuv_codec.cpp``) or None — the conversion runs
    per request on the serving host's core, and the numpy version's
    channel-interleaved reductions cost ~2 ms per 256² tile where the
    single-pass C++ loop costs ~0.2 ms."""
    global _native_encode, _native_tried
    if _native_tried:
        return _native_encode
    _native_tried = True
    import ctypes

    from ..utils.native_build import load_native_function
    _native_encode = load_native_function(
        "yuv_codec.cpp", "libyuv_codec.so", "yuv420_encode",
        restype=ctypes.c_int,
        argtypes=[ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
                  ctypes.c_int, ctypes.POINTER(ctypes.c_uint8)])
    return _native_encode


def rgb_to_yuv420(arr: np.ndarray) -> np.ndarray:
    """(H, W, 3) uint8 RGB → flat planar uint8 [Y | Cb | Cr], chroma 2×2
    box-averaged. H and W must be even (tile sizes are). Dispatches to the
    C++ encoder when available (same contract within 1 LSB — rounding of
    exact halves differs); numpy otherwise."""
    if arr.ndim != 3 or arr.shape[-1] != 3 or arr.dtype != np.uint8:
        # Validate BEFORE dispatch: the C++ path reinterprets raw bytes and
        # would return plausible garbage for float/RGBA input with rc==0.
        raise ValueError(
            f"expected (H, W, 3) uint8, got {arr.shape} {arr.dtype}")
    h, w, _ = arr.shape
    if h % 2 or w % 2:
        raise ValueError(f"yuv420 needs even dims, got {arr.shape}")
    encode = _get_native_encode()
    if encode is not None:
        import ctypes

        arr = np.ascontiguousarray(arr)
        out = np.empty(yuv420_nbytes(h, w), np.uint8)
        rc = encode(arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    h, w, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if rc == 0:
            return out
    return _rgb_to_yuv420_numpy(arr)


def _rgb_to_yuv420_numpy(arr: np.ndarray) -> np.ndarray:
    h, w, _ = arr.shape
    n = h * w
    q = (h // 2) * (w // 2)
    out = np.empty(yuv420_nbytes(h, w), np.uint8)
    f = arr.astype(np.float32)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    y = 0.299 * r + 0.587 * g + 0.114 * b
    cb = 128.0 - 0.168736 * r - 0.331264 * g + 0.5 * b
    cr = 128.0 + 0.5 * r - 0.418688 * g - 0.081312 * b
    cb = cb.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    cr = cr.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    out[:n] = (y + 0.5).astype(np.uint8).reshape(-1)  # y ∈ [0,255] exactly
    out[n:n + q] = np.clip(np.round(cb), 0, 255).astype(np.uint8).reshape(-1)
    out[n + q:] = np.clip(np.round(cr), 0, 255).astype(np.uint8).reshape(-1)
    return out


def yuv420_to_rgb_numpy(flat: np.ndarray, h: int, w: int) -> np.ndarray:
    """Host-side inverse: flat planes → (H, W, 3) uint8 RGB — for consumers
    that need the image back on the HOST (e.g. a pipeline crops handoff
    cropping a yuv-wire detector's input). Same math as the device inverse."""
    flat = np.asarray(flat, np.uint8)
    n = h * w
    q = (h // 2) * (w // 2)
    y = flat[:n].reshape(h, w).astype(np.float32)
    cb = flat[n:n + q].reshape(h // 2, w // 2).astype(np.float32) - 128.0
    cr = flat[n + q:].reshape(h // 2, w // 2).astype(np.float32) - 128.0
    cb = np.repeat(np.repeat(cb, 2, axis=0), 2, axis=1)
    cr = np.repeat(np.repeat(cr, 2, axis=0), 2, axis=1)
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    rgb = np.stack([r, g, b], axis=-1)
    return np.clip(np.round(rgb), 0, 255).astype(np.uint8)


def yuv420_to_rgb(flat, h: int, w: int):
    """Device-side inverse: (B, yuv420_nbytes) uint8 → (B, H, W, 3) float32
    in [0, 1]. Chroma upsamples nearest (what fast JPEG decoders do); the
    whole thing is elementwise + reshape, so XLA fuses it into the consumer.
    """
    import jax.numpy as jnp

    n = h * w
    q = (h // 2) * (w // 2)
    bsz = flat.shape[0]
    y = flat[:, :n].reshape(bsz, h, w).astype(jnp.float32)
    cb = flat[:, n:n + q].reshape(bsz, h // 2, w // 2).astype(jnp.float32)
    cr = flat[:, n + q:].reshape(bsz, h // 2, w // 2).astype(jnp.float32)
    cb = jnp.repeat(jnp.repeat(cb, 2, axis=1), 2, axis=2) - 128.0
    cr = jnp.repeat(jnp.repeat(cr, 2, axis=1), 2, axis=2) - 128.0
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    rgb = jnp.stack([r, g, b], axis=-1)
    return jnp.clip(rgb / 255.0, 0.0, 1.0)
