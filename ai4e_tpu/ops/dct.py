"""DCT-truncation host↔device wire codec — JPEG-grade h2d compression whose
decoder is two small matmuls (MXU work), not entropy decoding.

The yuv420 wire (``ops/yuv.py``) halved h2d bytes and still left the chip
~80% idle behind the link on the image configs (r3:
``bench_results/r3-tpu/landcover_yuv.json`` — 170.8 req/s delivered vs 841
device capability). The remaining compression JPEG gets comes from the DCT:
after an 8×8 block transform, camera imagery concentrates its energy in the
low-frequency corner, and coarse quantization of the rest is visually
lossless. JPEG spends that insight on Huffman coding — sequential, hostile
to a vector unit. This codec spends it on a **fixed-rate** layout instead,
so the device can decode with dense linear algebra:

- host (``rgb_to_dct``): RGB → JPEG-convention YCbCr (chroma 2×2 subsampled,
  exactly the yuv420 front half) → per-plane 8×8 orthonormal DCT-II → keep
  the top-left K×K coefficients (K=4 default) → quantize by a JPEG-style
  table → int8. Bytes: ``K²/64`` per luma pixel + chroma at a quarter
  resolution — **0.375 B/px at K=4, 4× less than yuv420, 8× less than
  raw RGB** (a 256² tile ships 24.6 kB; JPEG q75 of the same tile is
  ~20-35 kB, so the wire matches JPEG's rate without its serial decode);
- device (``dct_to_rgb``): int8 → dequantize (elementwise table multiply)
  → inverse DCT via two K×8 matmuls per block (``einsum`` over the block
  grid — batched small matmuls the MXU tiles) → chroma upsample → YCbCr→RGB
  → [0,1] float. XLA fuses the whole chain into the model's first conv.

Fidelity is test-gated per family against the trained checkpoints
(``tests/test_dct_wire.py``), same discipline as the yuv wire: the codec
ships only where predictions match the rgb8 wire.
"""

from __future__ import annotations

import numpy as np

# JPEG Annex K base quantization tables (quality 50), top-left 8×8. Scaled
# to the default quality below, then clamped so every kept coefficient of a
# level-shifted uint8 plane fits int8 (|DC| ≤ 1024 ⇒ quant ≥ 8).
_JPEG_LUMA_Q50 = np.array([
    [16, 11, 10, 16, 24, 40, 51, 61],
    [12, 12, 14, 19, 26, 58, 60, 55],
    [14, 13, 16, 24, 40, 57, 69, 56],
    [14, 17, 22, 29, 51, 87, 80, 62],
    [18, 22, 37, 56, 68, 109, 103, 77],
    [24, 35, 55, 64, 81, 104, 113, 92],
    [49, 64, 78, 87, 103, 121, 120, 101],
    [72, 92, 95, 98, 112, 100, 103, 99]], np.float32)
_JPEG_CHROMA_Q50 = np.array([
    [17, 18, 24, 47, 99, 99, 99, 99],
    [18, 21, 26, 66, 99, 99, 99, 99],
    [24, 26, 56, 99, 99, 99, 99, 99],
    [47, 66, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99],
    [99, 99, 99, 99, 99, 99, 99, 99]], np.float32)

DEFAULT_K = 4
DEFAULT_QUALITY = 75


def quant_tables(k: int = DEFAULT_K, quality: int = DEFAULT_QUALITY
                 ) -> tuple[np.ndarray, np.ndarray]:
    """(luma, chroma) K×K quant tables at ``quality`` (JPEG's scaling
    formula), clamped to [8, 255] so quantized coefficients fit int8."""
    if not 1 <= quality <= 100:
        raise ValueError(f"quality must be 1..100, got {quality}")
    scale = (5000.0 / quality if quality < 50 else 200.0 - 2.0 * quality) / 100.0
    out = []
    for base in (_JPEG_LUMA_Q50, _JPEG_CHROMA_Q50):
        t = np.clip(np.round(base[:k, :k] * scale), 8.0, 255.0)
        out.append(t.astype(np.float32))
    return out[0], out[1]


def dct_matrix() -> np.ndarray:
    """(8, 8) orthonormal DCT-II basis: ``coef = B @ block @ B.T``."""
    n = np.arange(8, dtype=np.float64)
    basis = np.cos(np.pi * (2 * n[None, :] + 1) * n[:, None] / 16.0)
    basis *= np.sqrt(2.0 / 8.0)
    basis[0] /= np.sqrt(2.0)
    return basis.astype(np.float32)


def dct_nbytes(h: int, w: int, k: int = DEFAULT_K) -> int:
    """Wire bytes for an (h, w) frame: K² int8 per 8×8 luma block, chroma
    blocks at quarter resolution."""
    return (h // 8) * (w // 8) * k * k + 2 * (h // 16) * (w // 16) * k * k


def _check_dims(h: int, w: int) -> None:
    if h % 16 or w % 16:
        # 8 for the luma block grid × 2 for chroma subsampling.
        raise ValueError(f"dct wire needs dims divisible by 16, got {h}x{w}")


def _plane_to_coeffs(plane: np.ndarray, k: int, qtable: np.ndarray,
                     basis: np.ndarray) -> np.ndarray:
    """(H, W) float (level-shifted) → (H/8, W/8, k, k) int8."""
    hb, wb = plane.shape[0] // 8, plane.shape[1] // 8
    blocks = plane.reshape(hb, 8, wb, 8).transpose(0, 2, 1, 3)
    coef = np.einsum("ka,nmab,lb->nmkl", basis[:k], blocks, basis[:k],
                     optimize=True)
    return np.clip(np.round(coef / qtable), -127, 127).astype(np.int8)


_native_encode = None
_native_tried = False


def _get_native_encode():
    """C++ encoder (``native/dct_codec.cpp``) or None — the conversion runs
    per request on the serving host's event loop, and the numpy path costs
    ~2.6 ms per 256² tile (~10.6 ms at 512²) where the single-pass C++
    loop is ~5-10x cheaper (and bit-exact on this toolchain)."""
    global _native_encode, _native_tried
    if _native_tried:
        return _native_encode
    _native_tried = True
    import ctypes

    from ..utils.native_build import load_native_function
    _native_encode = load_native_function(
        "dct_codec.cpp", "libdct_codec.so", "dct_encode",
        restype=ctypes.c_int,
        argtypes=[ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
                  ctypes.c_int, ctypes.c_int,
                  ctypes.POINTER(ctypes.c_float),
                  ctypes.POINTER(ctypes.c_float),
                  ctypes.POINTER(ctypes.c_int8)])
    return _native_encode


def rgb_to_dct(arr: np.ndarray, k: int = DEFAULT_K,
               quality: int = DEFAULT_QUALITY) -> np.ndarray:
    """(H, W, 3) uint8 RGB → flat int8 [Y coeffs | Cb | Cr], each plane in
    (blocks_y, blocks_x, k, k) row-major order. Dispatches to the C++
    encoder when available (same contract within 1 quant LSB — float
    association order differs); numpy otherwise."""
    if arr.ndim != 3 or arr.shape[-1] != 3 or arr.dtype != np.uint8:
        raise ValueError(
            f"expected (H, W, 3) uint8, got {arr.shape} {arr.dtype}")
    h, w, _ = arr.shape
    _check_dims(h, w)
    encode = _get_native_encode()
    if encode is not None:
        import ctypes

        arr_c = np.ascontiguousarray(arr)
        luma_q, chroma_q = quant_tables(k, quality)
        luma_q = np.ascontiguousarray(luma_q)
        chroma_q = np.ascontiguousarray(chroma_q)
        out = np.empty(dct_nbytes(h, w, k), np.int8)
        rc = encode(arr_c.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    h, w, k,
                    luma_q.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    chroma_q.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
        if rc == 0:
            return out
    return _rgb_to_dct_numpy(arr, k, quality)


def _rgb_to_dct_numpy(arr: np.ndarray, k: int = DEFAULT_K,
                      quality: int = DEFAULT_QUALITY) -> np.ndarray:
    h, w, _ = arr.shape
    f = arr.astype(np.float32)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    y = 0.299 * r + 0.587 * g + 0.114 * b
    cb = 128.0 - 0.168736 * r - 0.331264 * g + 0.5 * b
    cr = 128.0 + 0.5 * r - 0.418688 * g - 0.081312 * b
    cb = cb.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    cr = cr.reshape(h // 2, 2, w // 2, 2).mean(axis=(1, 3))
    luma_q, chroma_q = quant_tables(k, quality)
    basis = dct_matrix()
    parts = [
        _plane_to_coeffs(y - 128.0, k, luma_q, basis).reshape(-1),
        _plane_to_coeffs(cb - 128.0, k, chroma_q, basis).reshape(-1),
        _plane_to_coeffs(cr - 128.0, k, chroma_q, basis).reshape(-1),
    ]
    return np.concatenate(parts).view(np.int8)


def _coeffs_to_plane_jnp(coef, hb: int, wb: int, k: int, qtable, basis):
    """(B, hb, wb, k, k) int → (B, 8·hb, 8·wb) float32 via dequant + IDCT
    (``block = Bᵀ[:,:k] @ coef @ B[:k,:]``) — two small matmuls per block,
    batched over the grid; the MXU's favorite shape."""
    import jax.numpy as jnp

    bsz = coef.shape[0]
    deq = coef.astype(jnp.float32) * qtable
    blocks = jnp.einsum("ak,bnmkl,lc->bnmac", basis[:k].T, deq, basis[:k])
    return (blocks.transpose(0, 1, 3, 2, 4)
            .reshape(bsz, hb * 8, wb * 8))


def dct_to_rgb(flat, h: int, w: int, k: int = DEFAULT_K,
               quality: int = DEFAULT_QUALITY):
    """Device-side decode: (B, dct_nbytes) int8 → (B, H, W, 3) float32 in
    [0, 1]. Dense linear algebra only (dequant multiply, per-block IDCT
    matmuls, nearest chroma upsample, 3×3 color transform) — XLA fuses it
    into the consumer; no HBM round trip for the intermediate planes."""
    import jax.numpy as jnp

    _check_dims(h, w)
    hb, wb = h // 8, w // 8
    hcb, wcb = h // 16, w // 16
    n_y = hb * wb * k * k
    n_c = hcb * wcb * k * k
    luma_q, chroma_q = quant_tables(k, quality)
    basis = dct_matrix()
    bsz = flat.shape[0]
    coefs = flat.astype(jnp.int8)
    y = _coeffs_to_plane_jnp(
        coefs[:, :n_y].reshape(bsz, hb, wb, k, k),
        hb, wb, k, jnp.asarray(luma_q), jnp.asarray(basis)) + 128.0
    cb = _coeffs_to_plane_jnp(
        coefs[:, n_y:n_y + n_c].reshape(bsz, hcb, wcb, k, k),
        hcb, wcb, k, jnp.asarray(chroma_q), jnp.asarray(basis))
    cr = _coeffs_to_plane_jnp(
        coefs[:, n_y + n_c:].reshape(bsz, hcb, wcb, k, k),
        hcb, wcb, k, jnp.asarray(chroma_q), jnp.asarray(basis))
    cb = jnp.repeat(jnp.repeat(cb, 2, axis=1), 2, axis=2)
    cr = jnp.repeat(jnp.repeat(cr, 2, axis=1), 2, axis=2)
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    rgb = jnp.stack([r, g, b], axis=-1)
    return jnp.clip(rgb / 255.0, 0.0, 1.0)


def dct_to_rgb_numpy(flat: np.ndarray, h: int, w: int, k: int = DEFAULT_K,
                     quality: int = DEFAULT_QUALITY) -> np.ndarray:
    """Host-side inverse for consumers needing the image back on the host
    (crops handoffs) — same math as the device decode, uint8 output."""
    _check_dims(h, w)
    hb, wb = h // 8, w // 8
    hcb, wcb = h // 16, w // 16
    n_y = hb * wb * k * k
    n_c = hcb * wcb * k * k
    luma_q, chroma_q = quant_tables(k, quality)
    basis = dct_matrix()
    flat = np.asarray(flat).view(np.int8)

    def plane(coef, nb_h, nb_w, qtable):
        deq = coef.reshape(nb_h, nb_w, k, k).astype(np.float32) * qtable
        blocks = np.einsum("ak,nmkl,lc->nmac", basis[:k].T, deq, basis[:k],
                           optimize=True)
        return blocks.transpose(0, 2, 1, 3).reshape(nb_h * 8, nb_w * 8)

    y = plane(flat[:n_y], hb, wb, luma_q) + 128.0
    cb = plane(flat[n_y:n_y + n_c], hcb, wcb, chroma_q)
    cr = plane(flat[n_y + n_c:], hcb, wcb, chroma_q)
    cb = np.repeat(np.repeat(cb, 2, axis=0), 2, axis=1)
    cr = np.repeat(np.repeat(cr, 2, axis=0), 2, axis=1)
    r = y + 1.402 * cr
    g = y - 0.344136 * cb - 0.714136 * cr
    b = y + 1.772 * cb
    rgb = np.stack([r, g, b], axis=-1)
    return np.clip(np.round(rgb), 0, 255).astype(np.uint8)
