"""On-device Pallas kernel validation (VERDICT r1 next-round #5).

The three serving kernels (``flash_attention``, ``segmentation_argmax``,
``normalize_image``) default to interpret mode off-TPU, so CPU CI never
proves they compile to Mosaic and fit VMEM on real hardware. This module is
that proof: ``validate_kernels()`` runs each kernel with ``interpret=False``
(on TPU) against a pure-XLA oracle and asserts its working set fits the
per-core scoped-VMEM budget under double buffering. ``bench.py`` embeds the
result in its JSON (``"pallas_tpu"``) whenever the bench lands on a TPU, so
every driver bench run is also a kernel-validation artifact.

VMEM accounting mirrors each kernel's BlockSpecs (pallas_guide.md: Mosaic
double-buffers every in/out block; scratch is single-buffered).
"""

from __future__ import annotations

import jax
import numpy as np

# v4/v5e/v5p cores expose ~16 MiB of VMEM; stay under with headroom for
# Mosaic's own spills.
VMEM_BUDGET_BYTES = 16 * 1024 * 1024


def flash_attention_vmem_bytes(block_q: int, block_k: int, d: int,
                               dtype_bytes: int = 4) -> int:
    """Double-buffered q/k/v/out blocks + f32 scratch (acc, m, l)."""
    blocks = (block_q * d) + 2 * (block_k * d) + (block_q * d)
    scratch = (block_q * d + 2 * block_q) * 4
    return 2 * blocks * dtype_bytes + scratch


def segmentation_argmax_vmem_bytes(c: int, tile_h: int, w: int,
                                   dtype_bytes: int = 4) -> int:
    return 2 * ((c * tile_h * w) * dtype_bytes + tile_h * w * 1)


def normalize_image_vmem_bytes(tile_h: int, w: int, c: int) -> int:
    row = w * c
    return 2 * ((tile_h * row) * 1 + 2 * row * 4 + (tile_h * row) * 4)


def validate_kernels(interpret: bool = False) -> dict:
    """Run each kernel against its XLA oracle; returns per-kernel
    {ok, max_err, vmem_bytes}. ``interpret=True`` runs the same checks in the
    pallas interpreter (CPU CI coverage of this module's own logic)."""
    from .flash_attention import flash_attention
    from .image_preprocess import normalize_image
    from .seg_postprocess import segmentation_argmax

    results: dict[str, dict] = {}
    rng = np.random.default_rng(0)

    # flash attention vs naive softmax(QK^T)V — serving shape of the
    # long-context family (seqformer) at block 128.
    b, h, s, d = 2, 4, 512, 64
    q = rng.standard_normal((b, h, s, d)).astype(np.float32)
    k = rng.standard_normal((b, h, s, d)).astype(np.float32)
    v = rng.standard_normal((b, h, s, d)).astype(np.float32)
    got = np.asarray(jax.jit(
        lambda q, k, v: flash_attention(q, k, v, interpret=interpret)
    )(q, k, v))
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    want = np.einsum("bhqk,bhkd->bhqd", p / p.sum(-1, keepdims=True), v)
    err = float(np.max(np.abs(got - want)))
    vmem = flash_attention_vmem_bytes(128, 128, d)
    assert vmem <= VMEM_BUDGET_BYTES, f"flash attention VMEM {vmem}"
    # Tolerance is set by the arithmetic of the executing backend, not the
    # kernel (or the interpret flag — interpret-mode jnp ops still run on
    # the default device): at DEFAULT precision the TPU MXU truncates f32
    # matmul operands to bf16 (~8 mantissa bits), so vs the f64-exact numpy
    # oracle the attention output carries ~4e-3 absolute error at these
    # scales (r2 measured 2.5e-3 on v5e, identically under interpret=True).
    # CPU runs true f32 (~1e-6) and keeps the tight bound so CPU CI still
    # catches sub-1e-2 kernel-logic regressions.
    tol = 1e-2 if jax.default_backend() == "tpu" else 1e-4
    results["flash_attention"] = {
        "ok": bool(err < tol), "max_err": round(err, 6), "vmem_bytes": vmem}

    # segmentation argmax vs jnp.argmax — the land-cover serving shape.
    bb, hh, ww, cc = 2, 256, 256, 4
    logits = rng.standard_normal((bb, hh, ww, cc)).astype(np.float32)
    got_map = np.asarray(jax.jit(
        lambda x: segmentation_argmax(x, interpret=interpret))(logits))
    want_map = np.argmax(logits, -1).astype(np.uint8)
    seg_ok = bool((got_map == want_map).mean() > 0.9999)  # fp ties tolerated
    vmem = segmentation_argmax_vmem_bytes(cc, 64, ww)
    assert vmem <= VMEM_BUDGET_BYTES, f"segmentation argmax VMEM {vmem}"
    results["segmentation_argmax"] = {
        "ok": seg_ok,
        "max_err": float((got_map != want_map).mean()),
        "vmem_bytes": vmem}

    # uint8 normalize vs XLA arithmetic — the tile ingestion shape.
    img = rng.integers(0, 256, (2, 256, 256, 3), dtype=np.uint8)
    mean, std = (0.45, 0.45, 0.4), (0.22, 0.22, 0.25)
    got_n = np.asarray(jax.jit(
        lambda x: normalize_image(x, mean=mean, std=std,
                                  interpret=interpret))(img))
    want_n = ((img.astype(np.float32) / 255.0 - np.asarray(mean))
              / np.asarray(std))
    err = float(np.max(np.abs(got_n - want_n)))
    vmem = normalize_image_vmem_bytes(64, 256, 3)
    assert vmem <= VMEM_BUDGET_BYTES, f"normalize VMEM {vmem}"
    results["normalize_image"] = {
        "ok": bool(err < 1e-5), "max_err": round(err, 7), "vmem_bytes": vmem}

    results["all_ok"] = all(r["ok"] for r in results.values()
                            if isinstance(r, dict))
    results["interpret"] = interpret
    return results
