from .image_preprocess import normalize_image
from .seg_postprocess import (
    class_histogram,
    fused_seg_postprocess,
    segmentation_argmax,
)

__all__ = [
    "normalize_image",
    "class_histogram",
    "fused_seg_postprocess",
    "segmentation_argmax",
]
