from .flash_attention import flash_attention
from .image_preprocess import normalize_image
from .seg_postprocess import (
    class_histogram,
    fused_seg_postprocess,
    segmentation_argmax,
)

__all__ = [
    "flash_attention",
    "normalize_image",
    "class_histogram",
    "fused_seg_postprocess",
    "segmentation_argmax",
]
