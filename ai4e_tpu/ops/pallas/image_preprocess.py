"""Pallas TPU kernel: fused uint8 image normalization.

Input path hot op: clients send uint8 pixels; shipping uint8 to the device and
normalizing on-chip cuts host→device transfer 4× versus sending float32 (HBM
and interconnect bandwidth are the serving bottleneck, not FLOPs). The kernel
fuses cast → scale → mean/std normalization in one VMEM pass.

Mean/std are per-channel scalars; with C small (3) they are passed as (1, C)
arrays and broadcast on the VPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _normalize_kernel(img_ref, mean_ref, std_ref, out_ref):
    # img_ref: (1, TH, W, C) uint8; out (1, TH, W, C) float32
    x = img_ref[0].astype(jnp.float32) * (1.0 / 255.0)
    mean = mean_ref[0]  # (C,)
    std = std_ref[0]
    out_ref[0] = (x - mean[None, None, :]) / std[None, None, :]


def normalize_image(images: jax.Array, mean=None, std=None,
                    tile_h: int = 64, interpret: bool | None = None) -> jax.Array:
    """(B, H, W, C) uint8 → (B, H, W, C) float32 in normalized range."""
    b, h, w, c = images.shape
    if images.dtype != jnp.uint8:
        raise ValueError(f"expected uint8 input, got {images.dtype}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tile_h = min(tile_h, h)
    if h % tile_h:
        raise ValueError(f"H={h} not divisible by tile_h={tile_h}")
    mean = jnp.asarray([0.0] * c if mean is None else mean, jnp.float32)
    std = jnp.asarray([1.0] * c if std is None else std, jnp.float32)

    return pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, w, c), jnp.float32),
        grid=(b, h // tile_h),
        in_specs=[
            pl.BlockSpec((1, tile_h, w, c), lambda i, j: (i, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile_h, w, c), lambda i, j: (i, j, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(images, mean[None], std[None])
