"""Pallas TPU kernel: fused uint8 image normalization.

Input path hot op: clients send uint8 pixels; shipping uint8 to the device and
normalizing on-chip cuts host→device transfer 4× versus sending float32 (HBM
and interconnect bandwidth are the serving bottleneck, not FLOPs). The kernel
fuses cast → scale → mean/std normalization in one VMEM pass.

Layout notes (pallas_guide.md tiling): a channels-last block (1, TH, W, C)
would put C=3 on the 128-lane axis and pad it 42× in VMEM. Instead the image
is viewed as (B, H, W·C) — a free reshape, C is the dense minor dim — so the
lane axis is fully utilized. The per-channel mean/std scalars become (W·C,)
rows with the channel pattern pre-tiled (computed once at trace time), and
the kernel is a pure row-broadcast multiply-add on the VPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _normalize_kernel(img_ref, scale_ref, bias_ref, out_ref):
    # img_ref: (1, TH, W*C) uint8; out: (1, TH, W*C) float32
    # normalized = (x/255 - mean) / std  ==  x * scale + bias  with
    # scale = 1/(255*std), bias = -mean/std (folded at trace time).
    # Mosaic has no direct u8→f32 cast; widen through int32 on the VPU.
    x = img_ref[0].astype(jnp.int32).astype(jnp.float32)
    out_ref[0] = x * scale_ref[0][None, :] + bias_ref[0][None, :]


def normalize_image(images: jax.Array, mean=None, std=None,
                    tile_h: int = 64, interpret: bool | None = None) -> jax.Array:
    """(B, H, W, C) uint8 → (B, H, W, C) float32 in normalized range."""
    b, h, w, c = images.shape
    if images.dtype != jnp.uint8:
        raise ValueError(f"expected uint8 input, got {images.dtype}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Largest divisor of H within the target keeps the grid exact for
    # non-multiple-of-64 sizes (224 → 56, 512 → 64) — but never below the
    # 8-sublane minimum Mosaic tiles f32 at: a prime-ish H would otherwise
    # silently degrade to (1, W·C) blocks and fail/crawl on device.
    tile_h = min(tile_h, h)
    while h % tile_h and tile_h > 8:
        tile_h -= 1
    if h % tile_h:
        raise ValueError(
            f"H={h} has no tile divisor >= 8; pad the image height "
            "(e.g. to a multiple of 8) before normalize_image")
    mean = jnp.asarray([0.0] * c if mean is None else mean, jnp.float32)
    std = jnp.asarray([1.0] * c if std is None else std, jnp.float32)

    scale_row = jnp.tile(1.0 / (255.0 * std), w)    # (W*C,)
    bias_row = jnp.tile(-mean / std, w)

    flat = images.reshape(b, h, w * c)
    out = pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, w * c), jnp.float32),
        grid=(b, h // tile_h),
        in_specs=[
            pl.BlockSpec((1, tile_h, w * c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w * c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w * c), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile_h, w * c), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(flat, scale_row[None], bias_row[None])
    return out.reshape(b, h, w, c)
