"""Pallas TPU kernel: fused segmentation postprocess (argmax → uint8 map).

The land-cover API's hottest non-matmul op: converting (B, H, W, C) float32
logits into a (B, H, W) uint8 class map. Done naively this reads 4·H·W·C
bytes and writes H·W·C intermediate softmax values; fused in one kernel it
reads the logits once and writes only the 1-byte class ids — a ~17×
write-bandwidth cut for C=4, which matters because the UNet's output layer is
HBM-bound, not MXU-bound.

Layout notes (pallas_guide.md tiling): a channels-last block (1, TH, W, C)
puts C on the 128-lane axis — C=4 pads to 128 lanes, inflating every VMEM
buffer 32× (a (1, 64, 256, 4) f32 block costs 8 MB instead of 256 KB and
blows the 16 MB scoped-VMEM budget under double buffering). So the array is
transposed to (B, C, H, W) first — one cheap XLA pass over the 4-channel
logits — and the kernel blocks as (1, C, TH, W): the (H, W) plane sits on
the (sublane, lane) axes at full utilization, and the class comparison
unrolls as C-1 vector max/select ops on the VPU.

Per-class pixel counts (the API's response payload) are computed outside the
kernel from the uint8 map — at 1 byte/pixel that second pass is ~0.4% of the
logits traffic, not worth fusing.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _argmax_kernel(logits_ref, out_ref, *, num_classes: int):
    # logits_ref: (1, C, TH, W); out_ref: (1, TH, W) uint8
    best = logits_ref[0, 0]
    idx = jnp.zeros(best.shape, jnp.int32)
    for c in range(1, num_classes):
        cand = logits_ref[0, c]
        take = cand > best
        best = jnp.where(take, cand, best)
        idx = jnp.where(take, c, idx)
    out_ref[0] = idx.astype(jnp.uint8)


def segmentation_argmax(logits: jax.Array, tile_h: int = 64,
                        interpret: bool | None = None) -> jax.Array:
    """(B, H, W, C) float32/bfloat16 logits → (B, H, W) uint8 class map.

    ``interpret`` defaults to True off-TPU so the same code path runs in CPU
    CI (pallas interpreter) and compiles to Mosaic on device.
    """
    b, h, w, c = logits.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tile_h = min(tile_h, h)
    if h % tile_h:
        raise ValueError(f"H={h} not divisible by tile_h={tile_h}")

    logits_cf = jnp.transpose(logits, (0, 3, 1, 2))  # (B, C, H, W)
    return pl.pallas_call(
        partial(_argmax_kernel, num_classes=c),
        out_shape=jax.ShapeDtypeStruct((b, h, w), jnp.uint8),
        grid=(b, h // tile_h),
        in_specs=[pl.BlockSpec((1, c, tile_h, w),
                               lambda i, j: (i, 0, j, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, tile_h, w), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(logits_cf)


def class_histogram(classmap: jax.Array, num_classes: int) -> jax.Array:
    """(B, H, W) uint8 → (B, num_classes) int32 pixel counts (XLA; cheap)."""
    onehot = jax.nn.one_hot(classmap, num_classes, dtype=jnp.int32)
    return jnp.sum(onehot, axis=(1, 2))


def fused_seg_postprocess(logits: jax.Array,
                          interpret: bool | None = None,
                          with_classmap: bool = True) -> dict:
    """Full API postprocess: per-class counts, plus the uint8 class map when
    ``with_classmap``. Histogram-only APIs pass False so the map never leaves
    the device — the counts are B·C int32s, ~4000× less device→host traffic
    than the map (which itself is 16× less than the logits)."""
    classmap = segmentation_argmax(logits, interpret=interpret)
    counts = class_histogram(classmap, logits.shape[-1])
    if with_classmap:
        return {"classmap": classmap, "counts": counts}
    return {"counts": counts}
