"""Pallas TPU kernel: fused flash attention (online-softmax, no S×S scores).

The long-context path's hottest op. Plain attention materialises a
(S_q, S_k) float32 score matrix per (batch, head) — at S=16k that is 1 GB
per head and pure HBM traffic. This kernel streams K/V blocks through VMEM
with a running max/denominator (the same online softmax the ring step uses
across devices, here applied across blocks within one device), so the score
matrix never exists: HBM traffic drops from O(S²) to O(S·D) and the two
matmuls land on the MXU back-to-back.

Role in the stack (``models/seqformer.py`` / ``parallel/ring_attention.py``):

- single-device long-context serving: ``attention_for(..., "flash")`` (the
  ``auto`` default off sequence-parallel meshes);
- inside Ulysses, each device attends over the full gathered sequence with
  1/n of the heads — that inner call is exactly this kernel's shape.

Layout (pallas_guide.md): grid is (B·H, S_q/block_q, S_k/block_k) — the K
dimension is a *grid* axis, not a whole-S_k VMEM block, so VMEM holds only
(block_q, D) + (block_k, D) tiles plus the (block_q, D) accumulator
regardless of sequence length (S=32k works in the same footprint as S=1k).
TPU grids execute sequentially with the rightmost axis fastest, so the
accumulator/max/denominator live in VMEM scratch carried across the k-axis
steps; the output block is written on the last k step. D rides the 128-lane
axis; block_q rides sublanes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-but-finite: avoids (-inf) - (-inf) NaNs in the kernel


def _flash_kernel(q_ref, k_ref, v_ref, out_ref, acc_ref, m_ref, l_ref, *,
                  n_k_blocks: int, causal: bool, scale: float):
    # q_ref/out_ref: (1, block_q, D); k_ref/v_ref: (1, block_k, D);
    # scratch: acc (block_q, D), m/l (block_q, 1) — carried across the
    # sequential k-axis grid steps.
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale
    kb = k_ref[0].astype(jnp.float32)
    vb = v_ref[0].astype(jnp.float32)
    scores = jax.lax.dot_general(
        q, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (bq, bk) on the MXU
    if causal:
        q_pos = (pl.program_id(1) * block_q
                 + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
        k_pos = (ik * block_k
                 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)

    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[...] = m_new
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ik == n_k_blocks - 1)
    def _finish():
        out_ref[0] = (acc_ref[...]
                      / jnp.maximum(l_ref[...], 1e-30)).astype(out_ref.dtype)


def _dividing_block(s: int, target: int) -> int:
    """Largest block size ≤ target that divides s (static shapes: the grid
    must tile the sequence exactly)."""
    for b in range(min(target, s), 0, -1):
        if s % b == 0:
            return b
    return 1


def default_blocks(d: int) -> tuple[int, int]:
    """Default (block_q, block_k) for head_dim ``d``.

    Tuned on TPU v5e at S=4096 D=128: 512/1024 measured 1.9x the 128/128
    blocks (74 vs 138 ms at B·H=128) at a ~3.4 MB double-buffered VMEM
    footprint (validate.py). VMEM cost scales linearly with D, so for
    D > 128 the tiles shrink proportionally (floor 128 — the sublane/lane
    minimum for fp32 tiling) to keep the footprint roughly constant rather
    than inheriting 4-8x bigger tiles that could exceed VMEM."""
    scale = max(1, d // 128)
    return max(128, 512 // scale), max(128, 1024 // scale)


def flash_attention(q, k, v, causal: bool = False, block_q: int | None = None,
                    block_k: int | None = None, interpret: bool | None = None,
                    mesh=None, batch_axes=None):
    """Fused attention: q (B, H, S_q, D), k/v (B, H, S_k, D) → (B, H, S_q, D).

    Block sizes round DOWN to divisors of the sequence lengths, so any length
    works (prime lengths degrade toward block 1 — pad such sequences).
    ``block_q``/``block_k`` default per head_dim via :func:`default_blocks`
    (512/1024 at D≤128, shrinking for larger D to bound VMEM).
    ``interpret`` defaults to True off-TPU (CPU CI runs the pallas
    interpreter; on device it compiles to Mosaic). ``mesh``/``batch_axes``
    are accepted (and ignored) so ``attention_for`` can treat this as a
    drop-in strategy alongside ring/Ulysses.
    """
    del mesh, batch_axes
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if causal and s_q != s_k:
        raise ValueError("causal flash attention expects S_q == S_k")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dq, dk = default_blocks(d)
    block_q = _dividing_block(s_q, block_q if block_q is not None else dq)
    block_k = _dividing_block(s_k, block_k if block_k is not None else dk)
    n_k_blocks = s_k // block_k

    def run(q3, k3, v3):
        # Collapsed (B·H, S, D) — one grid row per (batch, head).
        return pl.pallas_call(
            partial(_flash_kernel, n_k_blocks=n_k_blocks, causal=causal,
                    scale=d ** -0.5),
            grid=(q3.shape[0], s_q // block_q, n_k_blocks),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda bh, iq, ik: (bh, iq, 0)),
                pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
                pl.BlockSpec((1, block_k, d), lambda bh, iq, ik: (bh, ik, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda bh, iq, ik: (bh, iq, 0)),
            out_shape=jax.ShapeDtypeStruct((q3.shape[0], s_q, d), q3.dtype),
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(q3, k3, v3)

    out = run(q.reshape(b * h, s_q, d), k.reshape(b * h, s_k, d),
              v.reshape(b * h, s_k, d))
    return out.reshape(b, h, s_q, d)
