"""Pallas TPU kernel: fused flash attention (online-softmax, no S×S scores).

The long-context path's hottest op. Plain attention materialises a
(S_q, S_k) float32 score matrix per (batch, head) — at S=16k that is 1 GB
per head and pure HBM traffic. This kernel streams K/V blocks through VMEM
with a running max/denominator (the same online softmax the ring step uses
across devices, here applied across blocks within one device), so the score
matrix never exists: HBM traffic drops from O(S²) to O(S·D) and the two
matmuls land on the MXU back-to-back.

Differentiable (r5): a ``jax.custom_vjp`` with pallas backward kernels —
the FlashAttention-2 recurrence. The forward saves only O and the per-row
logsumexp (lane-replicated, the layout the TPU vector unit wants); the
backward recomputes P = exp(S - lse) blockwise, so training never
materialises the score matrix either. Before this, long-context TRAINING
fell back to full attention (``train/make_checkpoints.py`` trained seq-4096
against materialised 4096² scores); now the training plane matches the
serving plane.

Role in the stack (``models/seqformer.py`` / ``parallel/ring_attention.py``):

- single-device long-context serving: ``attention_for(..., "flash")`` (the
  ``auto`` default off sequence-parallel meshes);
- inside Ulysses, each device attends over the full gathered sequence with
  1/n of the heads — that inner call is exactly this kernel's shape.

Layout (pallas_guide.md): grid is (B·H, S_q/block_q, S_k/block_k) — the K
dimension is a *grid* axis, not a whole-S_k VMEM block, so VMEM holds only
(block_q, D) + (block_k, D) tiles plus the (block_q, D) accumulator
regardless of sequence length (S=32k works in the same footprint as S=1k).
TPU grids execute sequentially with the rightmost axis fastest, so the
accumulator/max/denominator live in VMEM scratch carried across the k-axis
steps; the output block is written on the last k step. D rides the 128-lane
axis; block_q rides sublanes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-but-finite: avoids (-inf) - (-inf) NaNs in the kernel


# The logsumexp residual rides lane-replicated (the official TPU flash
# kernel's layout): a (block_q,) per-row scalar broadcast across the
# 128-lane axis, so stores/loads are plain vector ops, never a transpose.
LANES = 128


def _mask_causal(s, iq, ik, block_q: int, block_k: int):
    """Set above-diagonal scores to NEG_INF for the (iq, ik) block pair —
    the one mask construction shared by the forward and both backward
    kernels."""
    q_pos = (iq * block_q
             + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
    k_pos = (ik * block_k
             + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _block_relevant(iq, ik, block_q: int, block_k: int):
    """False iff the (iq, ik) block pair lies strictly above the causal
    diagonal (its bottom-left corner is masked) — such blocks contribute
    nothing and are skipped, halving causal work."""
    return (iq + 1) * block_q - 1 >= ik * block_k


def _flash_kernel(q_ref, k_ref, v_ref, out_ref, *rest,
                  n_k_blocks: int, causal: bool, scale: float,
                  save_lse: bool):
    # q_ref/out_ref: (1, block_q, D); k_ref/v_ref: (1, block_k, D);
    # scratch: acc (block_q, D), m/l (block_q, 1) — carried across the
    # sequential k-axis grid steps. With ``save_lse`` an extra
    # (1, block_q, LANES) output carries m + log(l) for the backward.
    if save_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        (acc_ref, m_ref, l_ref), lse_ref = rest, None
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    # program_id must be read at the kernel's top level — inside a
    # pl.when branch it escapes the pallas trace (interpret mode lowers
    # the branch as plain XLA, where the primitive has no rule).
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (bq, bk) on the MXU
        if causal:
            scores = _mask_causal(scores, iq, ik, block_q, block_k)

        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # Blocks strictly above the diagonal contribute nothing — skip
        # their matmuls entirely (half the grid at S_q == S_k).
        pl.when(_block_relevant(iq, ik, block_q, block_k))(_accumulate)
    else:
        _accumulate()

    @pl.when(ik == n_k_blocks - 1)
    def _finish():
        out_ref[0] = (acc_ref[...]
                      / jnp.maximum(l_ref[...], 1e-30)).astype(out_ref.dtype)
        if lse_ref is not None:
            lse = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))
            lse_ref[0] = jnp.broadcast_to(lse, (block_q, LANES))


def _bwd_recompute(q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
                   iq, ik, causal: bool, scale: float):
    """Shared backward recompute — the FlashAttention-2 step both backward
    kernels start from: P = exp(S − lse) rebuilt blockwise (exact softmax
    probabilities; masked → 0) and dS = P ⊙ (dO·Vᵀ − Δ). Returns
    ``(p, ds, q, do, kb)`` — dK/dV contract against q/do, dQ against kb."""
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    kb = k_ref[0].astype(jnp.float32)
    vb = v_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]  # (block_q, 1) from the lane-replicated block
    di = di_ref[0][:, :1]

    s = scale * jax.lax.dot_general(
        q, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (bq, bk)
    if causal:
        s = _mask_causal(s, iq, ik, block_q, block_k)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, vb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    ds = p * (dp - di)
    return p, ds, q, do, kb


def _flash_bwd_dkv_kernel(q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          n_q_blocks: int, causal: bool, scale: float):
    """dK/dV: grid (B·H, S_k/block_k, S_q/block_q) — for a fixed k-block,
    accumulate contributions from every q-block in VMEM scratch (the q axis
    is the fast, sequential one), writing dk/dv on the last q step.
    P is recomputed from the saved logsumexp — no score matrix in HBM."""
    block_q, block_k = q_ref.shape[1], k_ref.shape[1]
    ik, iq = pl.program_id(1), pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _accumulate():
        p, ds, q, do, _ = _bwd_recompute(q_ref, do_ref, lse_ref, di_ref,
                                         k_ref, v_ref, iq, ik, causal, scale)
        # dV += Pᵀ·dO ; dK += scale·dSᵀ·Q
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[...] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_block_relevant(iq, ik, block_q, block_k))(_accumulate)
    else:
        _accumulate()

    @pl.when(iq == n_q_blocks - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
                         dq_ref, dq_acc, *,
                         n_k_blocks: int, causal: bool, scale: float):
    """dQ: grid (B·H, S_q/block_q, S_k/block_k) — the forward's own grid
    shape; accumulate over k-blocks, write dq on the last k step."""
    block_q, block_k = q_ref.shape[1], k_ref.shape[1]
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _accumulate():
        _, ds, _, _, kb = _bwd_recompute(q_ref, do_ref, lse_ref, di_ref,
                                         k_ref, v_ref, iq, ik, causal, scale)
        dq_acc[...] += scale * jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_block_relevant(iq, ik, block_q, block_k))(_accumulate)
    else:
        _accumulate()

    @pl.when(ik == n_k_blocks - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dividing_block(s: int, target: int) -> int:
    """Largest block size ≤ target that divides s (static shapes: the grid
    must tile the sequence exactly)."""
    for b in range(min(target, s), 0, -1):
        if s % b == 0:
            return b
    return 1


def default_blocks(d: int) -> tuple[int, int]:
    """Default (block_q, block_k) for head_dim ``d``.

    Tuned on TPU v5e at S=4096 D=128: 512/1024 measured 1.9x the 128/128
    blocks (74 vs 138 ms at B·H=128) at a ~3.4 MB double-buffered VMEM
    footprint (validate.py). VMEM cost scales linearly with D, so for
    D > 128 the tiles shrink proportionally (floor 128 — the sublane/lane
    minimum for fp32 tiling) to keep the footprint roughly constant rather
    than inheriting 4-8x bigger tiles that could exceed VMEM."""
    scale = max(1, d // 128)
    return max(128, 512 // scale), max(128, 1024 // scale)


def _forward_call(q3, k3, v3, causal: bool, block_q: int, block_k: int,
                  interpret: bool, save_lse: bool):
    """pallas_call for the forward on collapsed (B·H, S, D) operands;
    returns ``out`` or ``(out, lse)`` (lse lane-replicated f32)."""
    bh, s_q, d = q3.shape
    s_k = k3.shape[1]
    n_k_blocks = s_k // block_k
    out_shape = jax.ShapeDtypeStruct((bh, s_q, d), q3.dtype)
    out_spec = pl.BlockSpec((1, block_q, d), lambda b, iq, ik: (b, iq, 0))
    out_shapes, out_specs = out_shape, out_spec
    if save_lse:
        out_shapes = (out_shape,
                      jax.ShapeDtypeStruct((bh, s_q, LANES), jnp.float32))
        out_specs = (out_spec,
                     pl.BlockSpec((1, block_q, LANES),
                                  lambda b, iq, ik: (b, iq, 0)))
    return pl.pallas_call(
        partial(_flash_kernel, n_k_blocks=n_k_blocks, causal=causal,
                scale=d ** -0.5, save_lse=save_lse),
        grid=(bh, s_q // block_q, n_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, iq, ik: (b, ik, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash3(q3, k3, v3, causal, block_q, block_k, interpret):
    return _forward_call(q3, k3, v3, causal, block_q, block_k, interpret,
                         save_lse=False)


def _flash3_fwd(q3, k3, v3, causal, block_q, block_k, interpret):
    out, lse = _forward_call(q3, k3, v3, causal, block_q, block_k, interpret,
                             save_lse=True)
    # Store one f32 per row (the lanes are replicas).
    return out, (q3, k3, v3, out, lse[..., 0])


def _flash3_bwd(causal, block_q, block_k, interpret, residuals, do):
    q3, k3, v3, out, lse = residuals
    bh, s_q, d = q3.shape
    s_k = k3.shape[1]
    scale = d ** -0.5
    n_q_blocks, n_k_blocks = s_q // block_q, s_k // block_k
    # Δ = rowsum(dO ⊙ O) — the softmax-jacobian correction, O(S·D)
    # elementwise; computed here (XLA) and fed lane-replicated.
    di = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse_r = jnp.broadcast_to(lse[..., None], (bh, s_q, LANES))
    di_r = jnp.broadcast_to(di[..., None], (bh, s_q, LANES))

    q_spec_by_q = pl.BlockSpec((1, block_q, d), lambda b, ik, iq: (b, iq, 0))
    lm_spec_by_q = pl.BlockSpec((1, block_q, LANES),
                                lambda b, ik, iq: (b, iq, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, ik, iq: (b, ik, 0))
    dk3, dv3 = pl.pallas_call(
        partial(_flash_bwd_dkv_kernel, n_q_blocks=n_q_blocks, causal=causal,
                scale=scale),
        grid=(bh, n_k_blocks, n_q_blocks),
        in_specs=[q_spec_by_q, q_spec_by_q, lm_spec_by_q, lm_spec_by_q,
                  kv_spec, kv_spec],
        out_specs=(kv_spec, kv_spec),
        out_shape=(jax.ShapeDtypeStruct((bh, s_k, d), k3.dtype),
                   jax.ShapeDtypeStruct((bh, s_k, d), v3.dtype)),
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q3, do, lse_r, di_r, k3, v3)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, iq, ik: (b, iq, 0))
    lm_spec = pl.BlockSpec((1, block_q, LANES),
                           lambda b, iq, ik: (b, iq, 0))
    kv_spec_by_k = pl.BlockSpec((1, block_k, d),
                                lambda b, iq, ik: (b, ik, 0))
    dq3 = pl.pallas_call(
        partial(_flash_bwd_dq_kernel, n_k_blocks=n_k_blocks, causal=causal,
                scale=scale),
        grid=(bh, n_q_blocks, n_k_blocks),
        in_specs=[q_spec, q_spec, lm_spec, lm_spec,
                  kv_spec_by_k, kv_spec_by_k],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, do, lse_r, di_r, k3, v3)
    return dq3, dk3, dv3


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(q, k, v, causal: bool = False, block_q: int | None = None,
                    block_k: int | None = None, interpret: bool | None = None,
                    mesh=None, batch_axes=None):
    """Fused attention: q (B, H, S_q, D), k/v (B, H, S_k, D) → (B, H, S_q, D).

    Differentiable: ``jax.grad`` through this op runs the pallas backward
    kernels (FlashAttention-2 recurrence — P recomputed from the saved
    logsumexp, no S×S matrix in either pass).

    Block sizes round DOWN to divisors of the sequence lengths, so any length
    works (prime lengths degrade toward block 1 — pad such sequences).
    ``block_q``/``block_k`` default per head_dim via :func:`default_blocks`
    (512/1024 at D≤128, shrinking for larger D to bound VMEM).
    ``interpret`` defaults to True off-TPU (CPU CI runs the pallas
    interpreter; on device it compiles to Mosaic). ``mesh``/``batch_axes``
    are accepted (and ignored) so ``attention_for`` can treat this as a
    drop-in strategy alongside ring/Ulysses.
    """
    del mesh, batch_axes
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if causal and s_q != s_k:
        raise ValueError("causal flash attention expects S_q == S_k")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dq, dk = default_blocks(d)
    block_q = _dividing_block(s_q, block_q if block_q is not None else dq)
    block_k = _dividing_block(s_k, block_k if block_k is not None else dk)

    out = _flash3(q.reshape(b * h, s_q, d), k.reshape(b * h, s_k, d),
                  v.reshape(b * h, s_k, d), causal, block_q, block_k,
                  interpret)
    return out.reshape(b, h, s_q, d)
