"""Component launchers — ``python -m ai4e_tpu <component>``.

The reference deploys its components as separately-provisioned Azure
resources wired by 15 bash scripts (``InfrastructureDeployment/
deploy_infrastructure.sh:5-38``); here each node of a multi-host deployment
runs one launcher, configured by ``AI4E_*`` env vars (the typed sections in
``config.py``) plus a JSON spec file:

- ``control-plane --routes routes.json`` — gateway + task store (HTTP
  surface included) + broker + dispatchers + autoscalers in one process:
  the APIM + CacheManager + Service Bus + function-app tier.
- ``worker --models models.json`` — a TPU inference node: model runtime +
  micro-batcher + service shell, task state via HttpTaskManager against
  the control plane (the AKS model-container tier).
- ``reporter`` — cross-replica in-flight request counter (the reference's
  RequestReporter function app, ``deploy_request_reporter_function.sh``).

Spec formats (JSON):

routes.json::

    {"apis": [{"prefix": "/v1/landcover/classify-async",
               "backend": "http://worker:8081/v1/landcover/classify-async",
               // or a weighted canary set (same path, hosts differ):
               // "backends": [{"uri": "http://fleet:8081/v1/...", "weight": 95},
               //              {"uri": "http://canary:8081/v1/...", "weight": 5}],
               "mode": "async",             // or "sync"
               "autoscale": {"max_replicas": 8},   // optional
               "max_body_bytes": 67108864,  // optional edge payload cap
               "concurrency": 4}]}          // optional

models.json::

    {"models": [{"family": "unet", "name": "landcover", "tile": 256,
                 "buckets": [1, 16, 64],
                 "sync_path": "/classify",
                 "async_path": "/classify-async",
                 "batch": {"max_items": 512},     // optional batch API
                 "checkpoint": "/ckpts/landcover"}],  // optional weights
     "prefix": "v1/landcover"}
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import signal

from .config import ConfigError, FrameworkConfig

log = logging.getLogger("ai4e_tpu.cli")


def load_spec(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def build_control_plane(config: FrameworkConfig, routes: dict):
    """Assemble the control-plane process; returns the wired platform (its
    gateway app also carries the task-store HTTP surface)."""
    from .platform_assembly import LocalPlatform
    from .scaling import AutoscalePolicy
    from .taskstore.http import make_app as make_taskstore_app

    platform = LocalPlatform(config.to_platform_config())
    if config.gateway.api_keys is not None:
        # APIM front-door parity: published APIs require a subscription key.
        keys = {k.strip() for k in config.gateway.api_keys.split(",")
                if k.strip()}
        if not keys:
            # Fail CLOSED: a set-but-empty keys value means the operator
            # wanted auth; silently running open would invert that intent.
            raise ConfigError(
                "AI4E_GATEWAY_API_KEYS is set but contains no keys")
        platform.gateway.set_api_keys(keys)
    platform.gateway.max_body_bytes = config.gateway.max_body_bytes
    if config.gateway.rate_limit_rps or config.gateway.rate_limits:
        from .gateway.ratelimit import (RateLimit, RateLimiter,
                                        parse_rate_limits)
        per_key = parse_rate_limits(config.gateway.rate_limits or "")
        if config.gateway.rate_limit_rps:
            default = RateLimit(rps=config.gateway.rate_limit_rps,
                                burst=config.gateway.rate_limit_burst)
        else:
            # Only per-key limits were given: keys without one stay
            # unlimited (a very high default bucket).
            default = RateLimit(rps=1e9)
        platform.gateway.set_rate_limiter(RateLimiter(default,
                                                      per_key=per_key))
    if config.gateway.quota or config.gateway.quotas:
        from .gateway.ratelimit import (QuotaTracker, parse_quota,
                                        parse_quotas)
        per_key_q = parse_quotas(config.gateway.quotas or "")
        # default None: keys without a per-key quota are unlimited AND
        # untracked (no per-identity window bookkeeping).
        default_q = (parse_quota(config.gateway.quota)
                     if config.gateway.quota else None)
        platform.gateway.set_quota_tracker(QuotaTracker(default_q,
                                                        per_key=per_key_q))
    # The task-store HTTP surface rides on the gateway app — one
    # control-plane port serves the CACHE_CONNECTOR_*_URI endpoints remote
    # workers use (distributed_api_task.py:14-15 pattern). It enforces the
    # gateway's edge cap itself: the app's aiohttp cap is disabled.
    make_taskstore_app(platform.store, app=platform.gateway.app,
                       max_body_bytes=config.gateway.max_body_bytes,
                       max_result_bytes=config.gateway.max_result_bytes,
                       # Role flips over HTTP (promote/demote) must run the
                       # platform's full sequence — replication torn down
                       # before the store flip, transport started/stopped
                       # around it — not a bare store flip.
                       lifecycle=platform)
    # Typed API definitions ({org, api, backend_host, ...}) publish through
    # the registration customizer (gateway/registration.py) — one publish
    # code path; both spec styles can coexist in one routes.json.
    if routes.get("definitions"):
        from .gateway.registration import ApiDefinition, register_definitions
        register_definitions(platform, [ApiDefinition.from_dict(r)
                                        for r in routes["definitions"]])
    for api in routes.get("apis", []):
        mode = api.get("mode", "async")
        # "backend": one URI; "backends": weighted canary set
        # ([{"uri": ..., "weight": N}, ...] — utils/backends.py). Presence
        # check, not truthiness: an explicitly-empty "backends" must hit
        # normalize_backends' clear error, not silently fall back.
        backend = api["backends"] if "backends" in api else api["backend"]
        if mode == "sync":
            platform.publish_sync_api(api["prefix"], backend,
                                      max_body_bytes=api.get("max_body_bytes"))
            continue
        autoscale = api.get("autoscale")
        if api.get("internal"):
            # Pipeline-stage backend: transport consumer only, no public
            # gateway route (tasks arrive via handoff republish).
            platform.register_internal_route(
                backend,
                retry_delay=api.get("retry_delay"),
                concurrency=api.get("concurrency"),
                autoscale=AutoscalePolicy(**autoscale) if autoscale else None)
            continue
        platform.publish_async_api(
            api["prefix"], backend,
            retry_delay=api.get("retry_delay"),
            concurrency=api.get("concurrency"),
            autoscale=AutoscalePolicy(**autoscale) if autoscale else None,
            max_body_bytes=api.get("max_body_bytes"))
    return platform


def _declarative_handoff(spec: dict | None):
    """Translate a model spec's ``pipeline_to`` into a handoff callable —
    composite APIs as deployment data (the reference composes ensembles in
    code via AddPipelineTask, ``distributed_api_task.py:67-100``).

    ``{"endpoint": "/v1/models/classify-async",   # next stage's backend route
       "when_nonempty": "detections"}             # optional gate on the result

    An empty handoff body makes the store replay the task's ORIGINAL payload
    to the next stage (``CacheConnectorUpsert.cs:144-176`` semantics), so a
    detector can gate a classifier on the same image. When the gate field is
    empty/absent the stage completes the task itself.

    ``"payload": "crops"`` instead ships the detector's CROPS to the next
    stage's batch endpoint (``runtime/handoffs.crops_handoff``) — tune with
    ``crop_size`` / ``max_crops`` / ``min_score``:

    ``{"endpoint": "/v1/models/classify-species-batch-async",
       "payload": "crops", "crop_size": 224, "max_crops": 16}``
    """
    if not spec:
        return None
    endpoint = spec["endpoint"]
    if spec.get("payload") == "crops":
        from .runtime.handoffs import crops_handoff
        return crops_handoff(endpoint,
                             crop_size=spec.get("crop_size", 224),
                             max_crops=spec.get("max_crops", 16),
                             min_score=spec.get("min_score"))
    gate = spec.get("when_nonempty")

    def pipeline_to(result):
        if gate is not None:
            value = result.get(gate) if isinstance(result, dict) else None
            if not value:
                return None  # nothing to hand off — stage completes the task
        return endpoint, b""  # empty body → original-body replay downstream

    return pipeline_to


def _mesh_from_config(rt):
    """Build the serving mesh from the runtime section. Two sources,
    mutually exclusive:

    - ``AI4E_RUNTIME_MESH_SPEC`` — the declarative serving-mesh grammar
      ("dp=8", "dp=2,tp=2"; runtime/mesh/spec.py), validated against the
      visible device/process topology and served as a mesh endpoint
      (docs/mesh_serving.md);
    - the low-level AI4E_RUNTIME_DP/FSDP/TP/SP/EP axis sizes.

    All defaults (no spec, dp=0, rest=1) → None → ModelRuntime's
    all-devices data-parallel default."""
    from .runtime.mesh.spec import parse_mesh_spec
    layout = parse_mesh_spec(rt.mesh_spec)
    axes = dict(fsdp=rt.fsdp, tp=rt.tp, sp=rt.sp, ep=rt.ep)
    axes_set = rt.dp > 0 or any(v > 1 for v in axes.values())
    if layout is not None:
        if axes_set:
            raise ValueError(
                "AI4E_RUNTIME_MESH_SPEC and the AI4E_RUNTIME_DP/FSDP/TP/"
                "SP/EP axis knobs are mutually exclusive — the spec IS "
                "the serving mesh; unset the axis knobs")
        from .runtime.mesh.placement import mesh_for_layout
        return mesh_for_layout(layout)
    if not axes_set:
        return None
    import jax

    from .parallel import MeshSpec, make_mesh
    denom = max(1, rt.fsdp) * max(1, rt.tp) * max(1, rt.sp) * max(1, rt.ep)
    if rt.dp <= 0:
        if jax.device_count() % denom:
            raise ValueError(
                f"{jax.device_count()} devices not divisible by "
                f"fsdp*tp*sp*ep={denom} (AI4E_RUNTIME_* axis sizes)")
        dp = jax.device_count() // denom
    else:
        dp = rt.dp
    return make_mesh(MeshSpec(dp=dp, **{k: max(1, v)
                                        for k, v in axes.items()}))


def _restore_checkpoint(servable, checkpoint: str,
                        checkpoint_dir: str | None) -> None:
    """Restore a servable's params from a models-spec checkpoint —
    shared by the batch and streaming-LM paths so resolution cannot
    diverge. Relative paths resolve under ``checkpoint_dir``
    (AI4E_RUNTIME_CHECKPOINT_DIR, the chart's volume mount) or the
    working directory — orbax requires absolute paths. The path is
    recorded for the hot-reload endpoint (POST
    {prefix}/models/{name}/reload re-reads it)."""
    import os
    from .checkpoint import load_params
    if not os.path.isabs(checkpoint):
        checkpoint = os.path.abspath(os.path.join(
            checkpoint_dir or ".", checkpoint))
    servable.params = load_params(checkpoint, like=servable.params)
    servable.checkpoint_path = checkpoint
    log.info("restored %s params from %s", servable.name, checkpoint)


def build_worker(config: FrameworkConfig, models: dict):
    """Assemble a worker process; returns (worker, batcher, task_manager)."""
    from .runtime import (
        InferenceWorker,
        MicroBatcher,
        ModelRuntime,
        build_servable,
        enable_compilation_cache,
    )
    from .service.task_manager import (
        HttpResultStore,
        HttpTaskManager,
        LocalTaskManager,
    )

    rt = config.runtime
    enable_compilation_cache(rt.compile_cache_dir)
    # Multi-host slice: JAX_COORDINATOR_ADDRESS et al. initialise the DCN
    # plane (no-op single-process); the default mesh then spans every host.
    from .parallel import init_distributed
    init_distributed()
    runtime = ModelRuntime(mesh=_mesh_from_config(rt),
                           donate_batch=rt.donate_batch)

    store_base = models.get("taskstore") or config.gateway.taskstore_get_uri
    if store_base:
        # The chart mounts the gateway's comma-separated "keys" secret entry
        # directly; the worker authenticates with the first NON-EMPTY key
        # (same filtering as the gateway's parse — a leading comma must not
        # silently leave the worker keyless against a keyed store).
        key = next(
            (k.strip()
             for k in (config.service.taskstore_api_key or "").split(",")
             if k.strip()), None)
        # A comma-separated value is the control-plane REPLICA SET
        # (primary first, then standby — control-plane-standby.yaml): the
        # store client rotates on connection failure / 503-not-primary so a
        # failover needs no worker restart (_HttpStoreClient._request).
        if isinstance(store_base, str) and "," in store_base:
            store_base = [u.strip() for u in store_base.split(",")
                          if u.strip()]
        task_manager = HttpTaskManager(store_base, api_key=key)
        store = HttpResultStore(store_base, api_key=key)
        if config.service.result_dir:
            # Direct-to-storage results: large outputs write to the shared
            # result mount (same root the control plane serves via
            # AI4E_PLATFORM_RESULT_DIR) and only a pointer crosses the
            # control network — the reference's containers-write-to-blob
            # architecture.
            from .service.task_manager import DirectResultStore
            store = DirectResultStore(
                config.service.result_dir, store,
                threshold=config.service.result_offload_threshold)
    else:
        # Standalone worker (dev): own in-memory store. result_dir becomes
        # the store's OWN offload backend (no control plane to register
        # pointers with — DirectResultStore would be a wrapper around a
        # backend-less store and every large result would be refused).
        from .taskstore import InMemoryTaskStore
        result_backend = None
        threshold = None
        if config.service.result_dir:
            from .taskstore.results import FileResultBackend
            result_backend = FileResultBackend(config.service.result_dir)
            threshold = config.service.result_offload_threshold
        store = InMemoryTaskStore(result_backend=result_backend,
                                  result_offload_threshold=threshold)
        task_manager = LocalTaskManager(store)

    reporter = None
    if config.service.reporter_uri:
        # Cross-replica in-flight reporting (REQUEST_REPORTER_URI pattern,
        # ai4e_service.py:21,135-146).
        from .metrics import ProcessingReporterClient
        reporter = ProcessingReporterClient(config.service.reporter_uri,
                                            cluster=config.service.cluster)

    # Register every servable BEFORE the batcher exists: with ladder
    # derivation on, the ai4e_batch_size exposition buckets are built
    # from the servables' (possibly restored) ladders at batcher
    # construction, and the persisted-ladder restore must land before
    # warmup so a restarted worker AOT-warms the traffic-tuned ladder
    # (docs/device_path.md).
    to_serve: list[tuple] = []
    lm_specs: list[dict] = []
    for spec in models.get("models", []):
        spec = dict(spec)
        family = spec.pop("family")
        if family == "seqformer-lm":
            # Streaming decode servables ride the continuous-batching
            # engine, not the MicroBatcher — collected here, wired after
            # the worker exists (docs/streaming.md).
            lm_specs.append(spec)
            continue
        sync_path = spec.pop("sync_path", None)
        async_path = spec.pop("async_path", None)
        cap = spec.pop("maximum_concurrent_requests", 64)
        batch = spec.pop("batch", None)  # true | {serve_batch kwargs}
        checkpoint = spec.pop("checkpoint", None)
        pipeline_spec = spec.pop("pipeline_to", None)
        # Families that build mesh-aware compute (seqformer's sp attention)
        # receive the serving mesh; the rest ignore it via their **_ sink.
        spec.setdefault("mesh", runtime.mesh)
        servable = build_servable(family, **spec)
        if checkpoint:
            # Restore real weights at pod start (SURVEY.md §5: the slot the
            # reference fills by baking weights into container images;
            # ai4e_tpu.train.make_checkpoints produces them).
            _restore_checkpoint(servable, checkpoint, rt.checkpoint_dir)
        runtime.register(servable)
        to_serve.append((servable, sync_path, async_path, cap,
                         pipeline_spec, batch))

    ladders = None
    import jax
    if rt.ladder_derive and jax.process_count() > 1 and jax.process_index():
        # Only the mesh primary derives: followers mirror the primary's
        # executions in follower_loop and jit-compile new bucket shapes
        # the moment its descriptors carry them, so a follower-local
        # deriver would only desync the broadcast order
        # (docs/mesh_serving.md). This replaces the old blanket
        # multi-process refusal — the primary's deriver now warm-executes
        # through MultihostRuntime.prepare_buckets, which broadcasts the
        # dummies so the whole slice compiles in lockstep.
        log.info("ladder derivation: follower %d defers to the mesh "
                 "primary's derived ladder", jax.process_index())
    elif rt.ladder_derive:
        # Traffic-tuned bucket ladders (AI4E_RUNTIME_LADDER_*, docs/
        # device_path.md): restore any persisted derived ladder now —
        # BEFORE warmup — so the restarted worker compiles the tuned
        # ladder and its first serving call stamps execute, not compile.
        import os
        from .runtime.ladder import LadderManager
        ladders = LadderManager(
            runtime, window_s=rt.ladder_window_s,
            max_programs=rt.ladder_max_programs,
            period_s=rt.ladder_period_s, dwell_s=rt.ladder_dwell_s,
            persist_path=(rt.ladder_path or os.path.join(
                rt.compile_cache_dir, "ladders.json")))
        restored = ladders.restore()
        if restored:
            log.info("restored derived ladders for %s",
                     sorted(restored))

    batcher = MicroBatcher(runtime, max_wait_ms=rt.batch_max_wait_ms,
                           max_pending=rt.batch_max_pending,
                           pipeline_depth=rt.batch_pipeline_depth,
                           interactive_reserve=rt.batch_interactive_reserve,
                           priority_aging_s=rt.batch_priority_aging_s,
                           # Device-phase decomposition rides the same
                           # switch as the worker's ledger flushes
                           # (AI4E_OBSERVABILITY_HOP_LEDGER).
                           measure_phases=config.observability.hop_ledger,
                           ladder_manager=ladders,
                           double_buffer=rt.batch_double_buffer)
    admin_keys = None
    if config.gateway.api_keys is not None:
        # The reload surface is an operator action: gate it with the same
        # front-door secret the gateway checks (the reference's APIM keys;
        # the control plane reuses it for the taskstore too).
        admin_keys = {k.strip() for k in config.gateway.api_keys.split(",")
                      if k.strip()}
    worker = InferenceWorker(
        models.get("service_name", "tpu-worker"), runtime, batcher,
        task_manager=task_manager, prefix=models.get("prefix", "v1"),
        store=store, reporter=reporter,
        # Hot-reload confinement (ADVICE r5): checkpoints must resolve
        # under the configured checkpoint mount — without this, anyone who
        # can reach the worker port could swap the served weights to any
        # readable path. None (dev, no AI4E_RUNTIME_CHECKPOINT_DIR) keeps
        # the open single-host behavior.
        checkpoint_root=rt.checkpoint_dir,
        admin_api_keys=admin_keys,
        hop_ledger=config.observability.hop_ledger,
        drain_timeout_s=config.rollout.drain_timeout_ms / 1000.0)
    for servable, sync_path, async_path, cap, pipeline_spec, batch in to_serve:
        if config.rollout.generation:
            # The deploy generation this process serves (rollout/): the
            # rollout controller bumps it per respawn; 0 keeps the
            # registry default.
            servable.generation = config.rollout.generation
        worker.serve_model(servable, sync_path=sync_path,
                           async_path=async_path,
                           maximum_concurrent_requests=cap,
                           pipeline_to=_declarative_handoff(pipeline_spec))
        if batch:
            worker.serve_batch(servable,
                               **(batch if isinstance(batch, dict) else {}))
    runtime.warmup()

    # Continuous-batching decode path (AI4E_RUNTIME_DECODE_ENABLE,
    # docs/streaming.md): one engine per seqformer-lm spec, AOT-warmed
    # (prefill buckets + the step program) so nothing compiles on the
    # serving path. Gated twice: the knob AND a spec — neither alone
    # constructs an engine, keeping the default worker byte-identical.
    # serve_stream registers each engine on worker.decode_engines (the
    # reload endpoint and run_worker's start/stop read it there).
    if lm_specs and not rt.decode_enable:
        log.warning("models spec names %d seqformer-lm servable(s) but "
                    "AI4E_RUNTIME_DECODE_ENABLE is off — not serving them",
                    len(lm_specs))
    elif lm_specs and jax.process_count() > 1:
        log.warning("streaming decode is single-host only (the engine "
                    "loop owns the device); not serving %d seqformer-lm "
                    "servable(s)", len(lm_specs))
    elif lm_specs:
        from .runtime.decode import DecodeEngine
        from .runtime.kvcache import PagedDecodeRuntime, build_lm_servable
        for spec in lm_specs:
            async_path = spec.pop("async_path", None)
            cap = spec.pop("maximum_concurrent_requests", 64)
            checkpoint = spec.pop("checkpoint", None)
            spec.setdefault("max_len", rt.kv_max_len)
            lm = build_lm_servable(**spec)
            if checkpoint:
                _restore_checkpoint(lm, checkpoint, rt.checkpoint_dir)
            backend = PagedDecodeRuntime(
                lm, slots=rt.kv_slots,
                prompt_buckets=rt.decode_prompt_buckets or None)
            backend.warm()
            engine = DecodeEngine(backend,
                                  max_pending=rt.decode_max_pending,
                                  metrics=worker.service.metrics)
            worker.serve_stream(engine, async_path=async_path,
                                maximum_concurrent_requests=cap)
            log.info("decode engine %s: %d slots, max_len %d, prompt "
                     "buckets %s, cache %.1f MB", lm.name, backend.slots,
                     backend.max_len, backend.prompt_buckets,
                     backend.cache_nbytes() / 1e6)

    if jax.process_count() > 1:
        # Multi-host serving (SURVEY.md §7 hard part #3): the primary's
        # batcher broadcasts each batch so every process enters the same
        # compiled call; followers mirror in follower_loop (run_worker).
        from .parallel.multihost import MultihostRuntime
        mh = MultihostRuntime(runtime)
        worker.runtime = mh
        batcher.runtime = mh
        if ladders is not None:
            # Derivation dummies must enter through the broadcast so
            # followers mirror them (MultihostRuntime.prepare_buckets).
            ladders.runtime = mh

    from .runtime.mesh import parse_mesh_spec
    layout = parse_mesh_spec(rt.mesh_spec)
    if layout is not None:
        # Mesh serving plane (AI4E_RUNTIME_MESH_SPEC, docs/mesh_serving.md):
        # the worker serves through a validated MeshEndpoint — layout
        # checked against the live mesh, poison accounting wired to the
        # coordinator's follower-health state machine, per-process device
        # phases drained into hop ledgers. Outermost wrapper: it must see
        # the multihost runtime's poison gathers, not raw registry calls.
        from .runtime.mesh import EndpointHealth, MeshCoordinator, MeshEndpoint
        health = EndpointHealth()
        coordinator = MeshCoordinator(
            layout, health=health,
            process_count=jax.process_count(),
            process_index=jax.process_index(),
            unhealthy_after=rt.mesh_unhealthy_after)
        inner = worker.runtime
        if hasattr(inner, "poison_listener"):
            coordinator.attach(inner)
        endpoint = MeshEndpoint(inner, layout, health=health,
                                coordinator=coordinator)
        worker.runtime = endpoint
        batcher.runtime = endpoint
        log.info("mesh serving plane ON: %s (tier %s, %d devices, "
                 "process %d/%d)", layout.describe()["spec"],
                 layout.tier_label, layout.size, jax.process_index(),
                 jax.process_count())
    return worker, batcher, task_manager


async def run_control_plane(config: FrameworkConfig, routes: dict) -> None:
    from aiohttp import web

    platform = build_control_plane(config, routes)
    runner = web.AppRunner(platform.gateway.app)
    await runner.setup()
    site = web.TCPSite(runner, config.gateway.host, config.gateway.port)
    await site.start()
    await platform.start()
    vitals = None
    if config.observability.vitals:
        # Runtime vitals into the ASSEMBLY registry: loop lag / GC /
        # RSS land beside the serving metrics on this process's
        # /metrics (AI4E_OBSERVABILITY_VITALS, docs/observability.md).
        from .observability.vitals import VitalsSampler
        vitals = VitalsSampler(platform.metrics,
                               interval_s=config.observability
                               .vitals_interval)
        await vitals.start()
    # Operators grep startup lines for posture; admission changes the
    # public contract (sheds, expiry, computed Retry-After —
    # AI4E_PLATFORM_ADMISSION=1, docs/admission.md) and resilience changes
    # failure semantics (breakers, retries, 5xx-as-transient —
    # AI4E_PLATFORM_RESILIENCE=1, docs/resilience.md).
    journal_stats = (platform.store.journal_stats()
                     if hasattr(platform.store, "journal_stats") else {})
    posture = ("".join([
        ", admission control ON" if platform.admission is not None else "",
        ", resilience ON" if platform.resilience is not None else "",
        # Orchestration changes placement + overload semantics (deadline/
        # cost-aware picks, brownout ladder, predictive scaling —
        # AI4E_PLATFORM_ORCHESTRATION=1, docs/orchestration.md).
        (", orchestration ON"
         if platform.orchestration is not None else ""),
        # Sharding changes the durability/availability topology (per-shard
        # journals + failover — AI4E_PLATFORM_TASK_SHARDS, docs/sharding.md).
        (f", task store sharded x{platform.config.task_shards}"
         if platform.config.task_shards > 1 else ""),
        # Tenancy changes the admission contract per caller (tenant
        # quotas, fair lanes, per-tenant series — AI4E_TENANCY_ENABLED,
        # docs/tenancy.md).
        (f", tenancy ON ({len(platform.tenancy.registry.tenant_ids())}"
         f" tenants)"
         if getattr(platform, "tenancy", None) is not None else ""),
        # Observability adds the hop ledger + flight recorder
        # (AI4E_PLATFORM_OBSERVABILITY, docs/observability.md) and,
        # with objectives, the SLO burn-rate engine.
        (", observability ON"
         if platform.observability is not None else ""),
        (f", SLO engine ON ({len(platform.slo.objectives)} objectives)"
         if platform.slo is not None else ""),
        # Vitals change what /metrics reports about the PROCESS itself
        # (ai4e_process_* — AI4E_OBSERVABILITY_VITALS).
        ", vitals ON" if vitals is not None else "",
        # The fsync policy changes what an acknowledgment MEANS against
        # a machine crash (AI4E_TASKSTORE_FSYNC, docs/durability.md) —
        # logged whenever a journal is in play (single or sharded) so
        # the posture line names the durability contract in force.
        (f", journal fsync={journal_stats['fsync_policy']}"
         if journal_stats else "")]))
    log.info("control plane on %s:%s (%d routes%s)", config.gateway.host,
             config.gateway.port, len(platform.gateway.routes), posture)
    try:
        await _wait_for_termination()
    finally:
        if vitals is not None:
            await vitals.stop()
        await platform.stop()
        await runner.cleanup()


async def run_worker(config: FrameworkConfig, models: dict) -> None:
    from aiohttp import web

    worker, batcher, task_manager = build_worker(config, models)

    import jax
    if jax.process_count() > 1 and jax.process_index() != 0:
        # Follower host of a pod slice: no HTTP surface — mirror the
        # primary's batch executions until it shuts us down.
        log.info("follower %d/%d: entering mirror loop",
                 jax.process_index(), jax.process_count())
        await asyncio.to_thread(worker.runtime.follower_loop)
        return

    await batcher.start()
    for engine in getattr(worker, "decode_engines", []):
        await engine.start()
    runner = web.AppRunner(worker.service.app)
    await runner.setup()
    site = web.TCPSite(runner, config.service.host, config.service.port)
    await site.start()
    vitals = None
    if config.observability.vitals:
        # Same sampler as the control plane, in the worker's service
        # registry — loop lag here is what explains "the batch sat
        # ready while the loop was blocked".
        from .observability.vitals import VitalsSampler
        vitals = VitalsSampler(worker.service.metrics,
                               interval_s=config.observability
                               .vitals_interval)
        await vitals.start()
    log.info("worker on %s:%s serving %s%s%s%s%s%s", config.service.host,
             config.service.port, list(worker.runtime.models),
             # Mesh posture (docs/mesh_serving.md): the declared serving
             # layout doubles as the orchestration cost-tier label.
             (", mesh %s ON (tier %s)" % (
                 worker.runtime.layout.describe()["spec"],
                 worker.runtime.layout.tier_label)
              if hasattr(worker.runtime, "layout") else ""),
             ", vitals ON" if vitals is not None else "",
             # Device-path posture (docs/device_path.md): operators grep
             # these to confirm the traffic-tuned/overlapped hot path.
             ", ladder derivation ON" if batcher._ladders is not None
             else "",
             ", double-buffered transfers ON" if batcher._double else "",
             # Streaming posture (docs/streaming.md): the continuous-
             # batching decode engines this worker serves.
             (", streaming decode ON (%s)" % ", ".join(
                 e.backend.name
                 for e in getattr(worker, "decode_engines", []))
              if getattr(worker, "decode_engines", []) else ""))
    try:
        await _wait_for_termination()
    finally:
        if vitals is not None:
            await vitals.stop()
        await worker.service.drain(timeout=config.service.drain_timeout)
        await batcher.stop()
        for engine in getattr(worker, "decode_engines", []):
            await engine.stop()
        if jax.process_count() > 1:
            worker.runtime.shutdown_followers()
        if worker.service.reporter is not None:
            await worker.service.reporter.close()
        if hasattr(task_manager, "close"):
            await task_manager.close()
        if hasattr(worker.store, "close"):
            await worker.store.close()
        await runner.cleanup()


async def run_reporter(config: FrameworkConfig, port: int | None) -> None:
    """Standalone request-reporter node (the reference deploys it as its own
    function app, ``deploy_request_reporter_function.sh``)."""
    from aiohttp import web

    from .metrics import RequestReporterService

    svc = RequestReporterService()
    runner = web.AppRunner(svc.app)
    await runner.setup()
    site = web.TCPSite(runner, config.service.host, port or 8085)
    await site.start()
    log.info("request reporter on %s:%s", config.service.host, port or 8085)
    try:
        await _wait_for_termination()
    finally:
        await runner.cleanup()


async def _wait_for_termination() -> None:
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    log.info("termination signal; draining")


def main(argv=None) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    parser = argparse.ArgumentParser(prog="ai4e_tpu")
    sub = parser.add_subparsers(dest="component", required=True)

    cp = sub.add_parser("control-plane",
                        help="gateway + task store + broker + dispatchers")
    cp.add_argument("--routes", required=True, help="routes.json path")
    cp.add_argument("--port", type=int, default=None)

    wk = sub.add_parser("worker", help="TPU inference worker")
    wk.add_argument("--models", required=True, help="models.json path")
    wk.add_argument("--port", type=int, default=None)

    rp = sub.add_parser("reporter",
                        help="cross-replica in-flight request reporter")
    rp.add_argument("--port", type=int, default=None)

    rd = sub.add_parser(
        "redrive",
        help="re-dispatch dead-lettered (or otherwise failed) tasks — the "
             "Service Bus Explorer resubmit workflow, against the store's "
             "ORIG replay")
    rd.add_argument("--store", default="http://127.0.0.1:8080",
                    help="control-plane URL (the task-store surface)")
    rd.add_argument("--task-id", default=None,
                    help="redrive ONE task (any failed state)")
    from .taskstore.task import TaskStatus as _TS
    rd.add_argument("--contains", default=_TS.DEAD_LETTER_PROSE,
                    help="sweep filter on the failed Status prose; '' "
                         "redrives every failed task")
    rd.add_argument("--api-key", default=None,
                    help="subscription key when the control plane runs "
                         "with gateway keys")

    tr = sub.add_parser(
        "trace",
        help="render task/request span trees from the JSONL trace log — "
             "the App Insights end-to-end transaction view, offline — "
             "or, with --url, a task's HOP LEDGER fetched live from the "
             "control plane (docs/observability.md)")
    tr.add_argument("--export", default=None,
                    help="span log path (default: the configured "
                         "AI4E_OBSERVABILITY_TRACE_EXPORT_PATH)")
    tr.add_argument("--url", default=None,
                    help="control-plane base URL: fetch the task's hop "
                         "ledger (GET /v1/taskmanagement/task/{id}"
                         "?ledger=1) instead of reading a span log; "
                         "requires --task-id")
    tr.add_argument("--api-key", default=None,
                    help="subscription key when the control plane runs "
                         "with gateway keys (--url mode)")
    tr_sel = tr.add_mutually_exclusive_group()
    tr_sel.add_argument("--task-id", default=None,
                        help="render every trace this task traversed")
    tr_sel.add_argument("--trace-id", default=None,
                        help="render one trace")
    tr.add_argument("--list", action="store_true", dest="list_traces",
                    help="summarize recent traces instead of rendering")
    tr.add_argument("--limit", type=int, default=20,
                    help="--list: how many recent traces")

    tp = sub.add_parser(
        "top",
        help="live fleet dashboard — per-proc req/s, goodput, SLO "
             "burn, event-loop lag, RSS from the federation snapshot "
             "(docs/observability.md)")
    tp.add_argument("--collector", default=None,
                    help="poll a collector's /v1/debug/fleet (the rig's "
                         "collector role)")
    tp.add_argument("--spec", default=None,
                    help="scrape a rig topology.json's roles directly")
    tp.add_argument("--targets", default=None,
                    help="ad-hoc name=url,name=url target list")
    tp.add_argument("--interval", type=float, default=2.0)
    tp.add_argument("--once", action="store_true",
                    help="print one frame and exit (scriptable)")

    tl = sub.add_parser(
        "timeline",
        help="export a rig run as ONE Chrome-trace/Perfetto JSON — hop "
             "ledgers, device phases, chaos verbs, vitals curves "
             "(load the output at https://ui.perfetto.dev)")
    tl.add_argument("--rig-dir", required=True,
                    help="rig artifact directory (rig.json + the "
                         "ledgers/vitals files the driver wrote)")
    tl.add_argument("--out", default=None,
                    help="output path (default <rig-dir>/timeline.json)")

    args = parser.parse_args(argv)

    if args.component == "top":
        # Pure fleet-snapshot client — no jax, no platform assembly.
        from .observability.top import run_top
        raise SystemExit(asyncio.run(run_top(
            collector=args.collector, spec=args.spec,
            targets=args.targets, interval=args.interval,
            once=args.once)))

    if args.component == "timeline":
        # Pure artifact transform — no jax, no platform assembly.
        import json as _json
        import os as _os

        from .observability.timeline import build_from_rig_dir
        if not _os.path.isdir(args.rig_dir):
            raise SystemExit(f"timeline: {args.rig_dir} is not a "
                             "directory (pass the rig artifact dir "
                             "`--out` wrote)")
        if not any(_os.path.exists(_os.path.join(args.rig_dir, f))
                   for f in ("rig.json", "ledgers.json")):
            raise SystemExit(f"timeline: {args.rig_dir} has neither "
                             "rig.json nor ledgers.json — not a rig "
                             "artifact directory")
        doc = build_from_rig_dir(args.rig_dir)
        out_path = args.out or _os.path.join(args.rig_dir,
                                             "timeline.json")
        with open(out_path, "w", encoding="utf-8") as fh:
            _json.dump(doc, fh)
        meta = doc["otherData"]
        print(f"wrote {out_path}: {len(doc['traceEvents'])} events, "
              f"{meta['tasks']} tasks, hops {meta['hops']}, "
              f"{len(meta['procs'])} procs — load it at "
              "https://ui.perfetto.dev")
        return

    if args.component == "trace":
        if args.url:
            # Live hop-ledger mode — pure HTTP client, no jax, no
            # assembly: one GET answers "where did this task's time go"
            # across every process it traversed.
            if not args.task_id:
                raise SystemExit("--url mode requires --task-id")
            import json as _json
            import urllib.error
            import urllib.request

            from .observability.ledger import render_ledger
            req = urllib.request.Request(
                args.url.rstrip("/")
                + f"/v1/taskmanagement/task/{args.task_id}?ledger=1",
                headers=({"Ocp-Apim-Subscription-Key": args.api_key}
                         if args.api_key else {}))
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    record = _json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                raise SystemExit(
                    f"task fetch failed: HTTP {exc.code} "
                    f"{exc.read().decode(errors='replace')[:200]}")
            except OSError as exc:
                raise SystemExit(f"cannot reach {args.url}: {exc}")
            print(render_ledger(args.task_id, record.get("Ledger") or [],
                                status=record.get("Status")))
            return
        # Pure log reader — no jax, no platform assembly.
        from .observability.traceview import (load_spans, render_list,
                                              render_trace, select_traces)
        path = args.export
        if path is None:
            path = FrameworkConfig.from_env().observability.trace_export_path
        if not path:
            raise SystemExit(
                "no span log: pass --export or set "
                "AI4E_OBSERVABILITY_TRACE_EXPORT_PATH on the services")
        try:
            spans = load_spans(path)
        except OSError as exc:
            raise SystemExit(f"cannot read span log {path}: {exc}")
        selected = select_traces(spans, task_id=args.task_id,
                                 trace_id=args.trace_id)
        if not selected and (args.task_id or args.trace_id):
            # A filter that matches nothing must fail loudly in both
            # modes — an empty --list reading as "zero-span traces" would
            # mislead scripted callers.
            raise SystemExit("no matching spans")
        if args.list_traces:
            # --list composes with the filters: summarize the SELECTED
            # traces (all of them when no filter given).
            print(render_list(selected, limit=args.limit))
            return
        if not selected:
            raise SystemExit("no matching spans")
        print(render_trace(selected))
        return

    if args.component == "redrive":
        # Pure HTTP client — no jax, no platform assembly.
        import json as _json
        import sys
        import urllib.error
        import urllib.request

        if args.task_id:
            payload: dict = {"TaskId": args.task_id}
        else:
            payload = {"Contains": args.contains}
        req = urllib.request.Request(
            args.store.rstrip("/") + "/v1/taskstore/redrive",
            data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     **({"Ocp-Apim-Subscription-Key": args.api_key}
                        if args.api_key else {})},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                print(resp.read().decode())
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode()
            if exc.code == 409:
                # The store evaluated the redrive and refused it: the
                # task is not in a redrivable (dead-lettered) status.
                print("redrive refused (409): task is not in a "
                      "redrivable status", file=sys.stderr)
            elif exc.code == 503:
                after = exc.headers.get("Retry-After") if exc.headers else None
                print("store refused the redrive (503"
                      + (f", retry after {after}s" if after else "")
                      + ") — standby or degraded; retry against the "
                      "primary", file=sys.stderr)
            print(detail)
            raise SystemExit(1)
        except OSError as exc:  # URLError/TimeoutError are OSErrors
            raise SystemExit(f"cannot reach {args.store}: {exc}")
        return
    config = FrameworkConfig.from_env()
    config.observability.apply()
    if config.runtime.platform:
        # Must be a config update, not an env var: the TPU plugin force-sets
        # jax_platforms at import, so AI4E_RUNTIME_PLATFORM=cpu is how a
        # CPU-only node (e.g. the control plane) opts out of device init.
        import jax
        jax.config.update("jax_platforms", config.runtime.platform)

    if args.component == "control-plane":
        if args.port is not None:
            config.gateway.port = args.port
        asyncio.run(run_control_plane(config, load_spec(args.routes)))
    elif args.component == "worker":
        if args.port is not None:
            config.service.port = args.port
        asyncio.run(run_worker(config, load_spec(args.models)))
    elif args.component == "reporter":
        asyncio.run(run_reporter(config, args.port))


if __name__ == "__main__":
    main()
