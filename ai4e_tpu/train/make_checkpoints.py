"""Deterministic checkpoint factory — real trained weights for the serving
configs (VERDICT r1 missing #1 / next-round #4).

The reference distributes weights by baking them into GPU container images
(``APIs/Charts/camera-trap/detection-async/prod-values.yaml:35-36`` pins a
TF-1.9 MegaDetector image); weights themselves live outside the repo and this
environment has no egress to fetch them. This module fills the same slot
reproducibly: each serving family is *trained to competence on a seeded
synthetic task* through the framework's own ``Trainer`` and saved via the
orbax path (``checkpoint.save_params``) that workers restore from at pod
start (``cli.build_worker``'s ``"checkpoint"`` key).

The tasks are synthetic but not fake — training must actually move each
model from chance to >=85% eval accuracy (asserted), so a loaded checkpoint
is distinguishable from random init by behavior, not just by bytes:

- **landcover** (UNet, BASELINE config #2): per-pixel classification of
  Voronoi-patch scenes where each land class has a characteristic color.
- **megadetector** (CenterNet, config #3): detection of colored shapes —
  animal/person/vehicle distinguished by color and aspect — trained with the
  CenterNet focal + L1 objective against gaussian center heatmaps.
- **species** (ResNet, config #4): 8-way classification of color x stripe
  orientation patterns (BatchNorm running stats frozen via a masked
  optimizer; only ``params`` train).

Models are fully convolutional (or globally pooled), so training runs at a
REDUCED resolution for speed and the same parameter tree serves at full
resolution — train 128x128, serve 512x512.

CLI: ``python -m ai4e_tpu.train.make_checkpoints --out checkpoints [--fast]``
writes ``checkpoints/{landcover,megadetector,species}`` + ``MANIFEST.json``.
"""

from __future__ import annotations

import json
import logging
import os

import numpy as np

log = logging.getLogger("ai4e_tpu.make_checkpoints")

STRIDE = 8  # CenterNet backbone stride (models/detector.py)

LANDCOVER_COLORS = np.array([  # water, forest, field, impervious
    [0.15, 0.25, 0.70], [0.10, 0.50, 0.15],
    [0.75, 0.70, 0.30], [0.50, 0.50, 0.55]], np.float32)

DETECTOR_COLORS = np.array([  # animal, person, vehicle
    [0.20, 0.70, 0.20], [0.80, 0.20, 0.20], [0.20, 0.30, 0.90]], np.float32)

SPECIES_LABELS = ["lion", "zebra", "elephant", "giraffe",
                  "leopard", "okapi", "rhino", "buffalo"]
SPECIES_COLORS = np.array([
    [0.80, 0.60, 0.20], [0.90, 0.90, 0.90],
    [0.45, 0.45, 0.50], [0.85, 0.70, 0.35]], np.float32)


# -- synthetic tasks (seeded, pure numpy) -----------------------------------

def landcover_batch(rng: np.random.Generator, batch: int, tile: int):
    """Voronoi land-class patches; image = class color + noise."""
    k = 5
    cy = rng.uniform(0, tile, (batch, k)).astype(np.float32)
    cx = rng.uniform(0, tile, (batch, k)).astype(np.float32)
    cls = rng.integers(0, len(LANDCOVER_COLORS), (batch, k))
    yy, xx = np.mgrid[0:tile, 0:tile].astype(np.float32)
    d = ((yy[None, :, :, None] - cy[:, None, None, :]) ** 2
         + (xx[None, :, :, None] - cx[:, None, None, :]) ** 2)
    nearest = np.argmin(d, axis=-1)                      # (B, H, W)
    labels = cls[np.arange(batch)[:, None, None], nearest]
    img = LANDCOVER_COLORS[labels] + rng.normal(0, 0.08,
                                                (batch, tile, tile, 3))
    return (np.clip(img, 0, 1).astype(np.float32),
            labels.astype(np.int32))


def detector_batch(rng: np.random.Generator, batch: int, size: int):
    """1-2 colored boxes per scene with CenterNet training targets.

    Object dimensions are ABSOLUTE (anchored at a 128-px reference frame),
    not proportional to the canvas: a bigger scene means more background
    around same-sized animals — the actual camera-trap statistics
    (MegaDetector's value is finding small animals in large frames), and
    the regime the backbone's ~59 px receptive field can learn. Canvas-
    proportional objects at 512 (85-256 px of flat color) make center
    localization impossible — every interior point looks identical —
    which is why the first 512 training run plateaued at 0.58."""
    h = size // STRIDE
    base = 128
    img = rng.normal(0.25, 0.05, (batch, size, size, 3)).astype(np.float32)
    heat = np.zeros((batch, h, h, 3), np.float32)
    wh = np.zeros((batch, h, h, 2), np.float32)
    off = np.zeros((batch, h, h, 2), np.float32)
    mask = np.zeros((batch, h, h, 1), np.float32)
    yy, xx = np.mgrid[0:h, 0:h].astype(np.float32)
    for b in range(batch):
        for _ in range(int(rng.integers(1, 3))):
            c = int(rng.integers(0, 3))
            if c == 0:    # animal: squarish
                bh = bw = int(rng.integers(base // 6, base // 3))
            elif c == 1:  # person: tall
                bh = int(rng.integers(base // 4, base // 2))
                bw = int(rng.integers(base // 12, base // 6))
            else:         # vehicle: wide
                bh = int(rng.integers(base // 12, base // 6))
                bw = int(rng.integers(base // 4, base // 2))
            cyp = rng.uniform(bh / 2, size - bh / 2)
            cxp = rng.uniform(bw / 2, size - bw / 2)
            y0, x0 = int(cyp - bh / 2), int(cxp - bw / 2)
            img[b, y0:y0 + bh, x0:x0 + bw] = (
                DETECTOR_COLORS[c]
                + rng.normal(0, 0.05, (bh, bw, 3)).astype(np.float32))
            gy, gx = cyp / STRIDE, cxp / STRIDE
            iy, ix = int(gy), int(gx)
            sigma = max(1.0, (bh + bw) / (6 * STRIDE))
            g = np.exp(-((yy - gy) ** 2 + (xx - gx) ** 2) / (2 * sigma ** 2))
            heat[b, :, :, c] = np.maximum(heat[b, :, :, c], g)
            heat[b, iy, ix, c] = 1.0
            wh[b, iy, ix] = (bh / STRIDE, bw / STRIDE)
            off[b, iy, ix] = (gy - iy, gx - ix)
            mask[b, iy, ix, 0] = 1.0
    targets = {"heatmap": heat, "wh": wh, "offset": off, "mask": mask}
    return np.clip(img, 0, 1), targets


def species_batch(rng: np.random.Generator, batch: int, size: int):
    """8 classes = 4 coat colors x 2 stripe orientations."""
    cls = rng.integers(0, 8, batch)
    color = SPECIES_COLORS[cls % 4]                      # (B, 3)
    vertical = (cls // 4).astype(bool)
    period = max(4, size // 8)
    ramp = (np.arange(size) // period) % 2               # (S,)
    img = np.empty((batch, size, size, 3), np.float32)
    for b in range(batch):
        stripes = ramp[:, None] if vertical[b] else ramp[None, :]
        m = np.broadcast_to(stripes, (size, size))[..., None]
        img[b] = m * color[b] + (1 - m) * 0.12
    img += rng.normal(0, 0.05, img.shape).astype(np.float32)
    return np.clip(img, 0, 1), cls.astype(np.int32)


def detection_accuracy(out, targets, score_floor: float = 0.15,
                       wh_rel_tolerance: float | None = None
                       ) -> tuple[int, int]:
    """Per-object detection accuracy against ``detector_batch`` targets —
    THE eval criterion the convergence gate ships checkpoints on, shared
    with the wire-fidelity tests so both always measure the same thing:
    a ground-truth object counts as hit when a decoded detection above
    ``score_floor`` lands within 1.5·STRIDE of its center with the right
    class. ``wh_rel_tolerance`` additionally requires the matched
    detection's box extent within that relative error of the true extent
    (regression-head coverage). Returns ``(hits, total_objects)``."""
    hits = total = 0
    for b in range(len(targets["mask"])):
        centers = np.argwhere(targets["mask"][b, :, :, 0] > 0)
        boxes = np.asarray(out["boxes"][b])
        classes = np.asarray(out["classes"][b])
        scores = np.asarray(out["scores"][b])
        for iy, ix in centers:
            total += 1
            true_cls = int(np.argmax(targets["heatmap"][b, iy, ix]))
            cy, cx = (iy + 0.5) * STRIDE, (ix + 0.5) * STRIDE
            det_cy = (boxes[:, 0] + boxes[:, 2]) / 2
            det_cx = (boxes[:, 1] + boxes[:, 3]) / 2
            near = ((np.abs(det_cy - cy) < 1.5 * STRIDE)
                    & (np.abs(det_cx - cx) < 1.5 * STRIDE)
                    & (scores > score_floor))
            if not near.any():
                continue
            best = np.flatnonzero(near)[np.argmax(scores[near])]
            if int(classes[best]) != true_cls:
                continue
            if wh_rel_tolerance is not None:
                true_h, true_w = targets["wh"][b, iy, ix] * STRIDE
                det_h = boxes[best, 2] - boxes[best, 0]
                det_w = boxes[best, 3] - boxes[best, 1]
                if (abs(det_h - true_h) > wh_rel_tolerance * true_h
                        or abs(det_w - true_w) > wh_rel_tolerance * true_w):
                    continue
            hits += 1
    return hits, total


# -- losses -----------------------------------------------------------------

def centernet_loss(outputs: dict, t: dict):
    """CenterNet objective: penalty-reduced focal on the heatmap + masked L1
    on size/offset at object centers."""
    import jax
    import jax.numpy as jnp

    heat = jax.nn.sigmoid(outputs["heatmap"].astype(jnp.float32))
    pos = (t["heatmap"] >= 0.999).astype(jnp.float32)
    neg_w = jnp.power(1.0 - t["heatmap"], 4.0)
    eps = 1e-6
    pos_l = -jnp.log(heat + eps) * jnp.power(1.0 - heat, 2.0) * pos
    neg_l = (-jnp.log(1.0 - heat + eps) * jnp.power(heat, 2.0)
             * neg_w * (1.0 - pos))
    n_pos = jnp.maximum(pos.sum(), 1.0)
    l_heat = (pos_l.sum() + neg_l.sum()) / n_pos
    l_wh = (jnp.abs(outputs["wh"] - t["wh"]) * t["mask"]).sum() / n_pos
    l_off = (jnp.abs(outputs["offset"] - t["offset"]) * t["mask"]).sum() / n_pos
    return l_heat + 0.1 * l_wh + l_off


# -- training recipes -------------------------------------------------------

def _trainer(apply_fn, params, loss_fn, lr, freeze_batch_stats=False):
    import jax
    import optax

    from ..parallel import MeshSpec, make_mesh
    from .step import Trainer

    # 1-device mesh: checkpoint production is a reproducible offline step
    # (multi-chip training is exercised by Trainer's own TP tests).
    mesh = make_mesh(MeshSpec(), devices=jax.devices()[:1])
    optimizer = optax.adamw(lr, weight_decay=1e-5)
    if freeze_batch_stats:
        labels = jax.tree_util.tree_map_with_path(
            lambda path, _: "freeze" if any(
                getattr(p, "key", None) == "batch_stats" for p in path)
            else "train", params)
        optimizer = optax.multi_transform(
            {"train": optimizer, "freeze": optax.set_to_zero()}, labels)
    return Trainer(apply_fn, params, mesh, loss_fn=loss_fn,
                   optimizer=optimizer)


def train_landcover(steps: int = 120, tile: int = 64, batch: int = 8,
                    seed: int = 0, widths=(64, 128, 256, 512),
                    lr: float = 1e-3) -> dict:
    """UNet on the Voronoi land-class task. Returns {params, eval_acc, ...}.

    NUM_CLASSES is the UNet's 4 land classes; ``kwargs`` in the result
    records the exact servable kwargs (widths, num_classes) the checkpoint
    restores into — deploy/specs/models.json must match or orbax restore
    fails at worker start.
    """
    from ..models import create_unet
    from ..models.unet import NUM_CLASSES
    from .step import segmentation_loss

    import jax

    model, params = create_unet(rng=jax.random.PRNGKey(seed), tile=tile,
                                widths=tuple(widths))
    tr = _trainer(model.apply, params, segmentation_loss, lr)
    rng = np.random.default_rng(seed)
    for step in range(steps):
        img, lab = landcover_batch(rng, batch, tile)
        loss = tr.train_step(img, lab)
        if step % 20 == 0:
            log.info("landcover step %d loss %.4f", step, float(loss))
    img, lab = landcover_batch(np.random.default_rng(seed + 1), batch, tile)
    pred = np.argmax(np.asarray(jax.jit(model.apply)(tr.params, img)), -1)
    acc = float((pred == lab).mean())
    log.info("landcover eval pixel-acc %.3f", acc)
    return {"params": tr.params, "eval": {"pixel_accuracy": round(acc, 4)},
            "family": "unet",
            "kwargs": {"widths": list(widths), "num_classes": NUM_CLASSES}}


def train_megadetector(steps: int = 150, image_size: int = 128,
                       batch: int = 8, seed: int = 0,
                       widths=(64, 128, 256)) -> dict:
    """CenterNet on the colored-shapes task; eval = top-detection class
    accuracy + center hit-rate via the real serving decode."""
    import jax

    from ..models import CenterNetDetector, decode_detections

    model = CenterNetDetector(widths=tuple(widths))
    params = model.init(jax.random.PRNGKey(seed),
                        np.zeros((1, image_size, image_size, 3), np.float32))
    tr = _trainer(model.apply, params, centernet_loss, 5e-4)
    rng = np.random.default_rng(seed)
    for step in range(steps):
        img, targets = detector_batch(rng, batch, image_size)
        loss = tr.train_step(img, targets)
        if step % 25 == 0:
            log.info("megadetector step %d loss %.4f", step, float(loss))

    # Eval over several batches: one batch of 8 scenes holds only ~12
    # objects, so a single borderline detection swings the measured accuracy
    # by ~8% — enough to flip the convergence gate on backend numerics alone
    # (observed 10/12 on TPU where CPU passed). ~48 objects is stable.
    eval_rng = np.random.default_rng(seed + 1)
    decode = jax.jit(lambda p, x: decode_detections(model.apply(p, x)))
    hits = total = 0
    for _ in range(4):
        img, targets = detector_batch(eval_rng, batch, image_size)
        out = decode(tr.params, img)
        h, t = detection_accuracy(out, targets)
        hits += h
        total += t
    acc = hits / max(total, 1)
    log.info("megadetector eval detection-acc %.3f (%d/%d)", acc, hits, total)
    return {"params": tr.params, "eval": {"detection_accuracy": round(acc, 4)},
            "family": "detector",
            # image_size rides in kwargs so SERVING happens at the trained
            # resolution: CenterNet features degrade off-scale (measured
            # 1.0 @128 → 0.5 @512 for 128-trained weights), so the size is
            # part of the weights' contract, not a free deployment knob.
            "kwargs": {"widths": list(widths), "image_size": image_size}}


def train_species(steps: int = 80, image_size: int = 64, batch: int = 16,
                  seed: int = 0, stage_sizes=(2, 2, 2), width: int = 32,
                  num_classes: int = 8) -> dict:
    """ResNet on the coat-pattern task (BatchNorm stats frozen)."""
    import jax

    from ..models.resnet import ResNet
    from .step import cross_entropy_loss

    model = ResNet(stage_sizes=tuple(stage_sizes), num_classes=num_classes,
                   width=width)
    variables = model.init(jax.random.PRNGKey(seed),
                           np.zeros((1, image_size, image_size, 3),
                                    np.float32))
    tr = _trainer(model.apply, variables, cross_entropy_loss, 1e-3,
                  freeze_batch_stats=True)
    rng = np.random.default_rng(seed)
    for step in range(steps):
        img, lab = species_batch(rng, batch, image_size)
        loss = tr.train_step(img, lab)
        if step % 20 == 0:
            log.info("species step %d loss %.4f", step, float(loss))
    img, lab = species_batch(np.random.default_rng(seed + 1), 32, image_size)
    logits = np.asarray(jax.jit(model.apply)(tr.params, img))
    acc = float((np.argmax(logits, -1) == lab).mean())
    log.info("species eval acc %.3f", acc)
    return {"params": tr.params, "eval": {"accuracy": round(acc, 4)},
            "family": "resnet",
            # image_size in kwargs: BatchNorm statistics and the receptive
            # field do NOT transfer across serving sizes (measured 1.0 @64
            # → 0.12 @224 for 64-trained weights) — serve at the trained
            # resolution.
            "kwargs": {"stage_sizes": list(stage_sizes), "width": width,
                       "num_classes": num_classes, "image_size": image_size,
                       "labels": SPECIES_LABELS}}


SPECIES_FINE_LABELS = ["serval", "genet", "civet", "caracal",
                       "duiker", "dikdik", "suni", "grysbok"]


def species_fine_batch(rng: np.random.Generator, batch: int, size: int):
    """Fine-grained TEXTURE classification — the task hard enough that a
    lossy wire can fail its fidelity gate (VERDICT r4 #6).

    8 classes = DCT-basis frequency u∈{2,3} × orientation {h,v} ×
    amplitude {high, faint}, on a constant gray base with noise: every bit
    of class information lives in the u=2/u=3 spectral bands of each 8-px
    block (the gratings are exact DCT-II basis functions,
    cos(uπ(2x+1)/16)), NOT in color or low-frequency structure. So the
    K=4 DCT wire (keeps u≤3) preserves it; K=2 (keeps u≤1) provably
    destroys it; and a ~4×-coarser quant table zeroes the FAINT half's
    coefficients (≈26 on the luma scale — survives the shipped q50 tables,
    quantizes to 0 once the u∈{2,3} table entries scale past ~52) — a
    fidelity gate with measurable failure boundaries on both the
    truncation and the quantization axis, unlike the color/shape tasks
    whose information survives any truncation. Amplitudes + base jitter +
    noise stay inside [0,1] (no clipping — clipping harmonics would leak
    amplitude information into bands the wire keeps)."""
    cls = rng.integers(0, 8, batch)
    u = 2 + (cls % 2)                      # DCT frequency index per block
    vertical = ((cls // 2) % 2).astype(bool)
    amp = np.where(cls < 4, 0.15, 0.018).astype(np.float32)
    x = np.arange(size, dtype=np.float32)
    img = np.empty((batch, size, size, 3), np.float32)
    for b in range(batch):
        wave = amp[b] * np.cos(np.pi * u[b] * (2 * x + 1) / 16.0)
        field = wave[:, None] if vertical[b] else wave[None, :]
        base = 0.45 + rng.uniform(-0.04, 0.04)
        img[b] = (base + np.broadcast_to(field, (size, size)))[..., None]
    # σ chosen against the faint amplitude (0.018 ≈ 4.6 gray levels): per-
    # coefficient SNR ≈ 3.4, hard enough that held-out accuracy stays
    # materially below 1.0 (VERDICT r4 #6) yet learnable in ~250 steps.
    img += rng.normal(0, 0.03, img.shape).astype(np.float32)
    return np.clip(img, 0, 1).astype(np.float32), cls.astype(np.int32)


def train_species_fine(steps: int = 250, image_size: int = 64,
                       batch: int = 16, seed: int = 0,
                       stage_sizes=(2, 2, 2), width: int = 32) -> dict:
    """ResNet on the fine-texture task. Same architecture/recipe as
    ``train_species``; the task (not the model) is the point — see
    ``species_fine_batch``. Held-out accuracy is expected materially below
    1.0 (amplitude discrimination under noise), unlike the saturated
    color/shape tasks."""
    import jax

    from ..models.resnet import ResNet
    from .step import cross_entropy_loss

    model = ResNet(stage_sizes=tuple(stage_sizes), num_classes=8,
                   width=width)
    variables = model.init(jax.random.PRNGKey(seed),
                           np.zeros((1, image_size, image_size, 3),
                                    np.float32))
    tr = _trainer(model.apply, variables, cross_entropy_loss, 1e-3,
                  freeze_batch_stats=True)
    rng = np.random.default_rng(seed)
    for step in range(steps):
        img, lab = species_fine_batch(rng, batch, image_size)
        loss = tr.train_step(img, lab)
        if step % 25 == 0:
            log.info("species_fine step %d loss %.4f", step, float(loss))
    apply = jax.jit(model.apply)
    eval_rng = np.random.default_rng(seed + 1)
    hits = total = 0
    for _ in range(4):  # 128 held-out images: a stable sub-1.0 estimate
        img, lab = species_fine_batch(eval_rng, 32, image_size)
        hits += int((np.argmax(np.asarray(apply(tr.params, img)), -1)
                     == lab).sum())
        total += len(lab)
    acc = hits / total
    log.info("species_fine eval acc %.3f", acc)
    return {"params": tr.params, "eval": {"accuracy": round(acc, 4)},
            "family": "resnet",
            "kwargs": {"stage_sizes": list(stage_sizes), "width": width,
                       "num_classes": 8, "image_size": image_size,
                       "labels": SPECIES_FINE_LABELS}}


def train_landcover128(steps: int = 120, **kw) -> dict:
    """128-px landcover checkpoint for the self-sizing CPU-fallback bench
    (VERDICT r4 weak #5: the artifact of record must never bench random
    weights). Trained at the standard 64 tile — the UNet is fully
    convolutional — but EVALUATED at the 128 serving tile, so the
    manifest's accuracy is honest at the geometry the fallback serves."""
    import jax

    from ..models import create_unet

    result = train_landcover(steps=steps, **kw)
    model, _ = create_unet(tile=128)
    img, lab = landcover_batch(np.random.default_rng(1), 8, 128)
    pred = np.argmax(
        np.asarray(jax.jit(model.apply)(result["params"], img)), -1)
    acc = float((pred == lab).mean())
    log.info("landcover128 eval pixel-acc %.3f (at the 128 serving tile)",
             acc)
    result["eval"] = {"pixel_accuracy_128": round(acc, 4)}
    result["kwargs"]["tile"] = 128
    return result


def longcontext_batch(rng: np.random.Generator, batch: int, seq_len: int,
                      vocab_size: int, num_classes: int = 16):
    """Marker-token classification: sequences of uniform-random background
    ids with ~3% of positions overwritten by the label class's marker id
    (the top ``num_classes`` ids of the vocab). The model must learn that
    rare marker embeddings — not the background distribution — carry the
    label: a long-context needle task solvable only through the embedding
    table + attention, so trained weights are behaviorally distinguishable
    from random init."""
    markers = max(4, seq_len // 32)
    toks = rng.integers(0, vocab_size - num_classes, (batch, seq_len))
    labels = rng.integers(0, num_classes, (batch,))
    for i in range(batch):
        pos = rng.choice(seq_len, size=markers, replace=False)
        toks[i, pos] = vocab_size - num_classes + labels[i]
    return toks.astype(np.int32), labels.astype(np.int32)


def _eval_marker_task(apply_fn, params, seq_len: int, vocab_size: int,
                      num_classes: int, seed: int, rounds: int = 4,
                      batch: int = 16) -> float:
    """Held-out accuracy on the marker task — the shared eval protocol for
    both sequence families (seed+1 convention, ~64 sequences so the gate is
    stable against backend numerics)."""
    import jax

    eval_rng = np.random.default_rng(seed + 1)
    apply = jax.jit(apply_fn)
    hits = total = 0
    for _ in range(rounds):
        toks, lab = longcontext_batch(eval_rng, batch, seq_len, vocab_size,
                                      num_classes)
        pred = np.argmax(np.asarray(apply(params, toks)), -1)
        hits += int((pred == lab).sum())
        total += len(lab)
    return hits / total


def resolve_train_attention(attention: str) -> str:
    """``train-auto`` → the right TRAINING attention for the backend: the
    differentiable pallas flash kernel on TPU (no S×S score matrix in
    either pass — the r5 custom_vjp; gradient parity pinned by
    ``test_pallas_ops.py::test_gradients_match_reference``), materialised
    "full" attention on CPU, where the pallas interpreter is slower than
    XLA at CI geometry. Any explicit strategy passes through untouched.
    The strategy carries no params, so the trained tree is identical
    either way."""
    if attention != "train-auto":
        return attention
    import jax

    resolved = "flash" if jax.default_backend() == "tpu" else "full"
    log.info("train-auto attention resolved to %r", resolved)
    return resolved


def train_longcontext(steps: int = 200, seq_len: int = 4096, batch: int = 8,
                      seed: int = 0, dim: int = 256, depth: int = 4,
                      heads: int = 2, vocab_size: int = 32768,
                      num_classes: int = 16, attention: str = "train-auto",
                      serve_attention: str = "flash",
                      lr: float = 1e-3) -> dict:
    """SeqFormer (token mode) on the marker task at the SERVING geometry —
    seq_len/vocab are baked into the parameter tree (pos_emb, Embed), so
    unlike the fully-convolutional families the trained shape IS the
    serving shape. Defaults = the bench/serving config (head_dim 128).

    ``attention`` is the TRAINING strategy; the default ``train-auto``
    resolves per backend: the differentiable flash kernel (r5 custom_vjp —
    no S×S score matrix in either pass, gradient parity pinned by
    ``test_pallas_ops.py::test_gradients_match_reference``) on TPU, where a
    window-opened fresh clone trains checkpoints on the chip; materialised
    "full" attention on CPU, where the pallas interpreter is slower than
    XLA at CI geometry. The strategy carries no params, so the tree is
    identical and ``serve_attention`` (recorded in the manifest kwargs) is
    what inference runs."""
    from ..models.seqformer import create_seqformer
    from .step import cross_entropy_loss

    attention = resolve_train_attention(attention)
    model, params = create_seqformer(
        seq_len=seq_len, input_dim=64, dim=dim, depth=depth, heads=heads,
        num_classes=num_classes, attention=attention, vocab_size=vocab_size)
    tr = _trainer(model.apply, params, cross_entropy_loss, lr)
    rng = np.random.default_rng(seed)
    for step in range(steps):
        toks, lab = longcontext_batch(rng, batch, seq_len, vocab_size,
                                      num_classes)
        loss = tr.train_step(toks, lab)
        if step % 25 == 0:
            log.info("longcontext step %d loss %.4f", step, float(loss))
    acc = _eval_marker_task(model.apply, tr.params, seq_len, vocab_size,
                            num_classes, seed)
    log.info("longcontext eval acc %.3f", acc)
    return {"params": tr.params, "eval": {"accuracy": round(acc, 4)},
            "family": "seqformer",
            # Everything serving needs to rebuild the exact tree: seq_len
            # and vocab_size are structural (pos_emb / Embed shapes).
            "kwargs": {"seq_len": seq_len, "input_dim": 64, "dim": dim,
                       "depth": depth, "heads": heads,
                       "num_classes": num_classes, "vocab_size": vocab_size,
                       "attention": serve_attention}}


def train_moe(steps: int = 200, seq_len: int = 1024, batch: int = 16,
              seed: int = 0, dim: int = 128, depth: int = 2, heads: int = 1,
              num_experts: int = 8, vocab_size: int = 8192,
              num_classes: int = 16, capacity_factor: float = 1.25,
              attention: str = "train-auto", serve_attention: str = "flash",
              lr: float = 1e-3) -> dict:
    """MoE classifier (token mode) on the same marker task as longcontext.

    Trains with **dense dispatch** (every expert runs every token — smooth
    gradients, bitwise deterministic) and **evaluates with the capacity
    dispatch it will serve** (GShard-style static capacity): the parameter
    tree is dispatch-independent, but overflow drops make capacity the
    stricter eval, so the gate certifies the weights as actually served.
    ``attention`` resolves like the longcontext recipe's ``train-auto``
    (flash on TPU, materialised full on CPU); serving runs
    ``serve_attention`` — no params either way."""
    from ..models.moe import create_moe
    from .step import cross_entropy_loss

    attention = resolve_train_attention(attention)

    model, params = create_moe(
        seq_len=seq_len, input_dim=64, dim=dim, depth=depth, heads=heads,
        num_experts=num_experts, num_classes=num_classes,
        attention=attention, dispatch="dense", vocab_size=vocab_size)
    tr = _trainer(model.apply, params, cross_entropy_loss, lr)
    rng = np.random.default_rng(seed)
    for step in range(steps):
        toks, lab = longcontext_batch(rng, batch, seq_len, vocab_size,
                                      num_classes)
        loss = tr.train_step(toks, lab)
        if step % 25 == 0:
            log.info("moe step %d loss %.4f", step, float(loss))
    # Same module, capacity dispatch (plain attributes — no re-init).
    serve_model = model.clone(dispatch="capacity",
                              capacity_factor=capacity_factor)
    acc = _eval_marker_task(serve_model.apply, tr.params, seq_len,
                            vocab_size, num_classes, seed)
    log.info("moe eval (capacity dispatch) acc %.3f", acc)
    return {"params": tr.params, "eval": {"accuracy": round(acc, 4)},
            "family": "moe",
            "kwargs": {"seq_len": seq_len, "input_dim": 64, "dim": dim,
                       "depth": depth, "heads": heads,
                       "num_experts": num_experts,
                       "num_classes": num_classes, "vocab_size": vocab_size,
                       "dispatch": "capacity",
                       "capacity_factor": capacity_factor,
                       "attention": serve_attention}}


RECIPES = {
    "landcover": train_landcover,
    "landcover128": train_landcover128,
    "megadetector": train_megadetector,
    "species": train_species,
    "species_fine": train_species_fine,
    "longcontext": train_longcontext,
    "moe": train_moe,
}

# Eval floor every produced checkpoint must clear — proof the weights are
# trained, not reshuffled noise (chance: landcover 0.25, megadetector
# ~0.33, species 0.125, longcontext 0.0625).
MIN_EVAL = 0.85


def make_checkpoint(name: str, out_dir: str, min_eval: float = MIN_EVAL,
                    **overrides) -> dict:
    """Train one recipe, assert competence, save under ``out_dir/name``."""
    from ..checkpoint import save_params

    result = RECIPES[name](**overrides)
    (metric_name, value), = result["eval"].items()
    if value < min_eval:
        raise AssertionError(
            f"{name}: {metric_name}={value} below {min_eval} — training did "
            "not converge; refusing to ship untrained weights")
    path = os.path.abspath(os.path.join(out_dir, name))
    save_params(path, result["params"])
    entry = {"family": result["family"], "kwargs": result["kwargs"],
             "eval": result["eval"], "path": path}
    log.info("saved %s -> %s (%s=%.3f)", name, path, metric_name, value)
    return entry


# Production training sizes = the serving sizes in deploy/specs/models.json.
# Accuracy does not transfer across input sizes (species measured 1.0@64 →
# 0.12@224 with 64-trained weights), so every full (non --fast) training —
# the CLI's and the bench's train-on-the-spot path — goes through these.
FULL_OVERRIDES = {
    # 300 steps at 512: the 150-step default converged to the gate's edge
    # (0.83-0.87 depending on backend numerics); doubling the schedule puts
    # the eval comfortably above the 0.85 floor on both CPU and TPU.
    "megadetector": {"image_size": 512, "steps": 300},
    "species": {"image_size": 224, "steps": 120},
}


def train_full(name: str, out_dir: str) -> dict:
    """Train ``name`` at production size and RECORD it in the manifest —
    the single entry point for producing a servable checkpoint outside CI
    (serving reads image_size from the manifest; a checkpoint without a
    manifest entry would be served at the wrong resolution)."""
    entry = make_checkpoint(name, out_dir, **FULL_OVERRIDES.get(name, {}))
    manifest_path = os.path.join(out_dir, "MANIFEST.json")
    manifest = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    manifest[name] = entry
    with open(manifest_path, "w") as f:
        json.dump(manifest, f, indent=2)
    return entry


def main(argv=None) -> None:
    import argparse

    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="checkpoints")
    parser.add_argument("--only", nargs="+", choices=sorted(RECIPES),
                        default=sorted(RECIPES))
    parser.add_argument("--fast", action="store_true",
                        help="fewer steps / smaller batches (CI smoke)")
    parser.add_argument("--platform", default="cpu",
                        help="jax_platforms value; 'cpu' (default) keeps the "
                             "run deterministic and immune to a degraded "
                             "remote-TPU tunnel (whose backend init hangs); "
                             "pass '' to use the session default backend")
    args = parser.parse_args(argv)

    import jax
    if args.platform:
        # Before any backend init — this host's sitecustomize pins
        # jax_platforms to the remote-TPU plugin, and probing it
        # (jax.default_backend()) hangs when the tunnel is degraded.
        jax.config.update("jax_platforms", args.platform)

    if (not args.fast and args.platform == "cpu"
            and "longcontext" in args.only):
        # Full-geometry longcontext on CPU trains seq-4096 FULL
        # attention (train-auto resolves to "full" off-TPU) — hours of
        # materialized 4096x4096 scores on one core. Warn rather than
        # refuse: the run is correct, just slow. On the TPU
        # (--platform '') train-auto picks the differentiable pallas
        # flash kernel by itself (resolve_train_attention).
        log.warning(
            "full longcontext training on jax_platforms=cpu materializes "
            "seq-4096 attention scores and can take hours; use "
            "--platform '' (TPU) or --fast for the CI geometry")
    # Full (default) runs train at the PRODUCTION serving sizes
    # (FULL_OVERRIDES); --fast keeps the recipes' small defaults for CI.
    fast = ({"landcover": {"steps": 60}, "landcover128": {"steps": 60},
             "megadetector": {"steps": 80},
             "species": {"steps": 65}, "species_fine": {"steps": 90},
             # Small geometry; training attention comes from the recipes'
             # train-auto default (resolve_train_attention: XLA full on
             # CPU CI, flash on TPU) — one source of truth for the rule.
             "longcontext": {"steps": 160, "seq_len": 256, "dim": 32,
                             "depth": 2, "heads": 2, "vocab_size": 512,
                             "batch": 16},
             "moe": {"steps": 160, "seq_len": 128, "dim": 32, "heads": 1,
                     "num_experts": 4, "vocab_size": 256, "batch": 16}}
            if args.fast else FULL_OVERRIDES)
    os.makedirs(args.out, exist_ok=True)
    manifest_path = os.path.join(args.out, "MANIFEST.json")
    manifest = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    for name in args.only:
        manifest[name] = make_checkpoint(name, args.out,
                                         **fast.get(name, {}))
    with open(manifest_path, "w") as f:
        json.dump(manifest, f, indent=2)
    print(json.dumps({k: v["eval"] for k, v in manifest.items()}))


if __name__ == "__main__":
    main()
