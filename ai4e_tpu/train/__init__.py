from .step import Trainer, cross_entropy_loss, segmentation_loss

__all__ = ["Trainer", "cross_entropy_loss", "segmentation_loss"]
