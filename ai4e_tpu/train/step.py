"""Sharded training step — fine-tuning support for served models.

The reference has no training at all (it serves frozen containers); the TPU
build gives every model family a mesh-sharded fine-tuning step so operators
can adapt models (e.g. per-region land-cover heads) on the same slice that
serves them. Data parallel over ``dp``/``fsdp``, tensor parallel per the
model's TP rules, optimizer state sharded like the params (optax tree maps
preserve shardings under jit).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import shard_params


def cross_entropy_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def segmentation_loss(logits, labels):
    """Per-pixel cross entropy for the UNet family."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


class Trainer:
    """Owns params + optimizer state placed on a mesh, and one jitted step.

    ``loss_fn(logits, labels)`` is scalar; gradients reduce over data axes
    automatically because the loss averages over the sharded batch dim and
    XLA inserts the psum — the annotate-and-compile recipe, no hand-written
    collectives.
    """

    def __init__(
        self,
        apply_fn: Callable,
        params: Any,
        mesh: Mesh,
        loss_fn: Callable = cross_entropy_loss,
        optimizer: optax.GradientTransformation | None = None,
        tp_rules: dict | None = None,
        remat: bool = False,
    ):
        self.mesh = mesh
        self.apply_fn = (jax.checkpoint(apply_fn) if remat else apply_fn)
        self.loss_fn = loss_fn
        self.optimizer = optimizer or optax.adamw(1e-4, weight_decay=1e-4)
        self.params = shard_params(params, mesh, tp_rules)
        self.opt_state = jax.jit(
            self.optimizer.init)(self.params)  # inherits param shardings

        batch_sharding = NamedSharding(mesh, P(("dp", "fsdp")))

        def step(params, opt_state, images, labels):
            def loss_of(p):
                logits = self.apply_fn(p, images)
                return self.loss_fn(logits, labels)

            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = jax.jit(
            step,
            in_shardings=(None, None, batch_sharding, batch_sharding),
            donate_argnums=(0, 1),
        )

    def train_step(self, images, labels) -> float:
        """One optimizer step; returns the scalar loss."""
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, images, labels)
        return float(loss)
