"""Inference result cache with single-flight coalescing bookkeeping.

The reference's Cache Manager stores only *task state* in Redis
(``ProcessManager/CacheManager/CacheConnectorUpsert.cs:40-213``); identical
inference requests always re-execute the model. At "millions of users" scale
re-execution is the dominant cost — one device batch runs ~5.1 s while every
transport hop is milliseconds (BENCH_r*), so each avoided execution is a
direct p50/p99 win. This module is the missing layer: a bounded, invalidatable
result store plus the in-flight registry that lets N concurrent identical
requests ride ONE execution (Clipper-style prediction caching + the
single-flight dedup pattern, PAPERS.md).

Design points:

- **LRU + TTL + byte budget.** Entries are evicted least-recently-used when
  either the entry count or the byte budget overflows; expired entries are
  dropped lazily on access and eagerly when an insert needs room. A single
  entry larger than ``max_entry_bytes`` is refused outright (one batch output
  must not wipe the whole cache).
- **Per-family invalidation.** Every key carries its family (model name or
  endpoint path — ``keys.family_of``); ``invalidate_family`` drops the whole
  namespace in one call. The worker's checkpoint hot-reload endpoint calls it
  so a stale result can never be served after a weight swap
  (``runtime/worker.py``).
- **Single-flight registry.** ``register_inflight(key, task_id)`` marks an
  execution as owning a key; ``leader_for`` lets the gateway hand late
  arrivals the SAME task record instead of creating (and executing) a new
  task; the store-listener wiring (``wiring.attach_store``) releases the
  registration on the leader's terminal transition.
- **Thread-safe.** Store listeners may fire from any thread; everything is
  guarded by one lock and every operation is O(1) amortized.

Metrics (``docs/METRICS.md``): ``ai4e_rescache_requests_total{outcome=}``
(hit|miss|coalesced|bypass), ``ai4e_rescache_evictions_total{reason=}``
(lru|bytes|ttl|invalidated|replaced|oversize), ``ai4e_rescache_entries``,
``ai4e_rescache_bytes``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from .keys import family_of


@dataclass
class _Entry:
    payload: bytes
    content_type: str
    family: str
    inserted_at: float
    # Families beyond the key's own that CONTRIBUTED to this result — a
    # pipeline composite is keyed under stage 1's endpoint but computed by
    # every downstream stage too; reloading ANY of them must drop it
    # (``invalidate_family`` matches these as well as ``family``).
    extra_families: tuple = ()


class ResultCache:
    """Bounded result store + in-flight request registry (one per process,
    shared by the gateway, dispatchers, and workers it serves)."""

    def __init__(self, max_entries: int = 4096,
                 max_bytes: int = 256 * 1024 * 1024,
                 ttl_s: float | None = 300.0,
                 max_entry_bytes: int | None = None,
                 metrics: MetricsRegistry | None = None,
                 clock=time.monotonic):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.ttl_s = ttl_s
        # Default: no single entry may take more than 1/8 of the byte budget
        # — a cache that holds at most a handful of giant batch outputs would
        # thrash instead of serving the interactive hot set.
        self.max_entry_bytes = (max_entry_bytes if max_entry_bytes is not None
                                else max(1, max_bytes // 8))
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self._bytes = 0
        # Single-flight: key -> task_id of the one execution owning it.
        self._inflight: dict[str, str] = {}
        # Per-family invalidation generation: bumped by invalidate_family so
        # a fill computed BEFORE an invalidation can prove it is stale and
        # refuse itself (``put(..., if_generation=)``). Families are routes/
        # models — a handful of keys, never unbounded.
        self._family_gen: dict[str, int] = {}
        metrics = metrics or DEFAULT_REGISTRY
        self._requests = metrics.counter(
            "ai4e_rescache_requests_total",
            "Result-cache lookups by outcome (hit/miss/coalesced/bypass)")
        self._evictions = metrics.counter(
            "ai4e_rescache_evictions_total",
            "Result-cache evictions by reason")
        self._entries_gauge = metrics.gauge(
            "ai4e_rescache_entries", "Result-cache live entries")
        self._bytes_gauge = metrics.gauge(
            "ai4e_rescache_bytes", "Result-cache resident payload bytes")

    # -- result store ------------------------------------------------------

    def get(self, key: str, count: bool = True) -> tuple[bytes, str] | None:
        """``(payload, content_type)`` or None; refreshes LRU recency.
        ``count=False`` skips the hit/miss counters — internal lookups
        (dispatcher redelivery check, worker sync path) pass it so one
        external request never records several outcomes and the hit ratio
        stays a statement about the gateway edge (docs/METRICS.md)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and self._expired(entry):
                self._drop(key, "ttl")
                # Keep the gauges honest through a read-only lull: without
                # this, lazy expiry leaves entries/bytes reporting pre-TTL
                # values until the next put/invalidate/sweep.
                self._sync_gauges()
                entry = None
            if entry is None:
                if count:
                    self._requests.inc(outcome="miss")
                return None
            self._entries.move_to_end(key)
            if count:
                self._requests.inc(outcome="hit")
            return entry.payload, entry.content_type

    def peek(self, key: str) -> bool:
        """Presence test without touching counters or recency (tests,
        introspection)."""
        with self._lock:
            entry = self._entries.get(key)
            return entry is not None and not self._expired(entry)

    def put(self, key: str, payload: bytes,
            content_type: str = "application/json",
            if_generation: int | None = None,
            extra_families: tuple = ()) -> bool:
        """Insert/overwrite; returns False when the entry is over the
        per-entry size cap (refused, nothing evicted for it) or when
        ``if_generation`` no longer matches the family's invalidation
        generation — a fill computed before a checkpoint reload invalidated
        the family is STALE and must not land (the sync proxy captures the
        generation when it becomes the single-flight leader)."""
        if len(payload) > self.max_entry_bytes:
            self._evictions.inc(reason="oversize")
            return False
        with self._lock:
            if (if_generation is not None
                    and if_generation != self._family_gen_locked(
                        family_of(key))):
                return False
            self._put_locked(key, payload, content_type, extra_families)
        return True

    def _put_locked(self, key: str, payload: bytes, content_type: str,
                    extra_families: tuple = ()) -> None:
        prev = self._entries.pop(key, None)
        if prev is not None:
            self._bytes -= len(prev.payload)
            self._evictions.inc(reason="replaced")
        self._entries[key] = _Entry(payload, content_type,
                                    family_of(key), self._clock(),
                                    tuple(extra_families))
        self._bytes += len(payload)
        self._shrink()
        self._sync_gauges()

    def generation(self, key: str) -> int:
        """The invalidation generation of ``key``'s family — capture before
        computing a result, pass back via ``put(if_generation=)`` so an
        invalidation that landed in between refuses the stale fill."""
        return self.family_generation(family_of(key))

    def family_generation(self, family: str) -> int:
        """Effective invalidation generation of a family NAME (not a key).
        Prefix-aware: invalidating ``/v1/x`` also advances ``/v1/x/tail`` —
        tailed request families belong to their base route's rollout unit."""
        with self._lock:
            return self._family_gen_locked(family)

    def _family_gen_locked(self, family: str) -> int:
        return sum(gen for fam, gen in self._family_gen.items()
                   if self._family_matches(family, fam))

    @staticmethod
    def _family_matches(family: str, invalidated: str) -> bool:
        """Whether invalidating ``invalidated`` covers ``family`` — exact, or
        ``family`` is a tailed sub-path of it (``/v1/x/op`` under ``/v1/x``)."""
        return (family == invalidated
                or family.startswith(invalidated + "/"))

    def invalidate_family(self, family: str) -> int:
        """Drop every entry a family contributed to — the checkpoint-reload
        hook. Matches the entry's own family (tailed sub-paths included) AND
        its ``extra_families`` (a pipeline composite keyed under stage 1 is
        dropped when a downstream stage's weights swap). Also clears the
        family's in-flight registrations: a leader executing on the OLD
        weights must not adopt post-swap subscribers (they re-execute on the
        new weights instead)."""
        with self._lock:
            self._family_gen[family] = self._family_gen.get(family, 0) + 1
            victims = [
                k for k, e in self._entries.items()
                if self._family_matches(e.family, family)
                or any(self._family_matches(x, family)
                       for x in e.extra_families)]
            for key in victims:
                self._drop(key, "invalidated")
            for key in [k for k in self._inflight
                        if self._family_matches(family_of(k), family)]:
                del self._inflight[key]
            self._sync_gauges()
            return len(victims)

    def sweep(self) -> int:
        """Eagerly drop expired entries (operational hook; lazy expiry covers
        normal operation). Returns entries dropped."""
        with self._lock:
            victims = [k for k, e in self._entries.items() if self._expired(e)]
            for key in victims:
                self._drop(key, "ttl")
            self._sync_gauges()
            return len(victims)

    # -- single-flight registry --------------------------------------------

    def register_inflight(self, key: str, task_id: str) -> bool:
        """Mark ``task_id`` as the one execution owning ``key``; False when
        another leader already holds it (caller should coalesce instead)."""
        with self._lock:
            if key in self._inflight:
                return False
            self._inflight[key] = task_id
            return True

    def leader_for(self, key: str) -> str | None:
        with self._lock:
            return self._inflight.get(key)

    def release_inflight(self, key: str, task_id: str) -> bool:
        """Drop the registration iff ``task_id`` still owns it (a stale
        release after re-registration must not orphan the new leader).
        Returns whether the caller owned it."""
        with self._lock:
            if self._inflight.get(key) == task_id:
                del self._inflight[key]
                return True
            return False

    def fill_inflight(self, key: str, task_id: str, payload: bytes,
                      content_type: str = "application/json",
                      family_gens: dict | None = None) -> bool:
        """Atomically: iff ``task_id`` still owns ``key``'s single-flight
        registration, store the result and release the registration. The
        async path's fill point (``wiring.attach_store``) — ownership is the
        staleness proof: a checkpoint reload's ``invalidate_family`` clears
        the registration, so a task that was already executing on the OLD
        weights fails this check and its result never lands (and a
        journal-restored task with no registration leaves the cache cold,
        never stale). ``family_gens`` extends the proof to DOWNSTREAM
        pipeline stages: ``{family: generation-at-handoff}`` captured when
        the task hopped to each stage — a stage whose weights swapped since
        its handoff refuses the fill (the registration only guards stage
        1's family). The checked families become the entry's
        ``extra_families`` so later reloads drop it too. False = nothing
        stored (a stale fill also releases the registration, so the next
        identical request re-executes on the new weights)."""
        if len(payload) > self.max_entry_bytes:
            with self._lock:
                owned = self._inflight.get(key) == task_id
                if owned:
                    del self._inflight[key]
            self._evictions.inc(reason="oversize")
            return False
        with self._lock:
            if self._inflight.get(key) != task_id:
                return False
            del self._inflight[key]
            if family_gens and any(
                    self._family_gen_locked(fam) != gen
                    for fam, gen in family_gens.items()):
                return False
            self._put_locked(key, payload, content_type,
                             tuple(family_gens) if family_gens else ())
            return True

    def count_hit(self) -> None:
        """Gateway-edge outcome counters: the edge calls ``get(count=False)``
        (a lookup that coalesces must not ALSO count as a miss) and records
        exactly one of hit/miss/coalesced/bypass once the outcome is known."""
        self._requests.inc(outcome="hit")

    def count_miss(self) -> None:
        self._requests.inc(outcome="miss")

    def count_coalesced(self) -> None:
        self._requests.inc(outcome="coalesced")

    def count_bypass(self) -> None:
        self._requests.inc(outcome="bypass")

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """Counter snapshot (bench/ops surface): hits, misses, coalesced,
        bypass, entries, resident bytes, in-flight keys."""
        with self._lock:
            entries, resident = len(self._entries), self._bytes
            inflight = len(self._inflight)
        return {
            "hits": self._requests.value(outcome="hit"),
            "misses": self._requests.value(outcome="miss"),
            "coalesced": self._requests.value(outcome="coalesced"),
            "bypass": self._requests.value(outcome="bypass"),
            "entries": entries,
            "bytes": resident,
            "inflight": inflight,
        }

    # -- internals (caller holds self._lock) --------------------------------

    def _expired(self, entry: _Entry) -> bool:
        return (self.ttl_s is not None
                and self._clock() - entry.inserted_at >= self.ttl_s)

    def _drop(self, key: str, reason: str) -> None:
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self._bytes -= len(entry.payload)
        self._evictions.inc(reason=reason)

    def _shrink(self) -> None:
        # TTL victims first — evicting a live LRU entry while expired ones
        # squat on the budget would shrink the effective cache for nothing.
        if self._bytes > self.max_bytes or len(self._entries) > self.max_entries:
            for key in [k for k, e in self._entries.items()
                        if self._expired(e)]:
                self._drop(key, "ttl")
        while len(self._entries) > self.max_entries:
            self._drop(next(iter(self._entries)), "lru")
        while self._bytes > self.max_bytes and self._entries:
            self._drop(next(iter(self._entries)), "bytes")

    def _sync_gauges(self) -> None:
        self._entries_gauge.set(len(self._entries))
        self._bytes_gauge.set(self._bytes)
