"""Inference result cache + single-flight request coalescing.

See ``docs/rescache.md`` for key derivation, invalidation-on-reload
semantics, coalescing guarantees, and the opt-out header.
"""

from .cache import ResultCache
from .keys import (BYPASS_HEADER, CACHE_STATUS_HEADER, cache_bypass_requested,
                   canonical_payload, family_of, normalize_media_type,
                   request_key)
from .wiring import attach_store

__all__ = [
    "ResultCache",
    "attach_store",
    "request_key",
    "canonical_payload",
    "normalize_media_type",
    "family_of",
    "cache_bypass_requested",
    "BYPASS_HEADER",
    "CACHE_STATUS_HEADER",
]
