"""Canonical request hashing — the cache's identity function.

A cache entry's key must be stable across every wire spelling of the *same*
inference request, and distinct for anything that could change the answer.
The digest therefore covers four dimensions:

- **family** — which servable/route answers the request (the worker uses the
  model name; the gateway uses the backend endpoint path, which is also the
  queue name — one invalidation namespace per rollout unit);
- **checkpoint** — which weights answer it (the worker keys on
  ``params_version`` so a hot reload naturally changes every key; the gateway
  does not know the serving version and relies on the reload invalidation
  hook instead — ``docs/rescache.md``);
- **wire format** — the payload's media type (an identical byte string means
  different things as ``image/jpeg`` vs ``application/x-npy``);
- **normalized payload bytes** — JSON payloads are re-serialized with sorted
  keys and canonical separators so ``{"a":1,"b":2}`` and
  ``{ "b": 2, "a": 1 }`` collide; binary payloads hash as-is.

Keys render as ``"{family}|{hexdigest}"`` so the family is recoverable for
invalidation bookkeeping without a reverse index (families are endpoint
paths or model names — neither may contain ``|``).
"""

from __future__ import annotations

import hashlib
import json

# Request header that opts a single request out of the result cache entirely
# (no read, no store). ``Cache-Control: no-cache`` / ``no-store`` are honored
# with the same meaning.
BYPASS_HEADER = "X-Cache-Bypass"
# Response header stamping the cache outcome: hit | miss | coalesced | bypass.
CACHE_STATUS_HEADER = "X-Cache"


def cache_bypass_requested(headers) -> bool:
    """True when the request opted out of the cache (``X-Cache-Bypass`` set,
    or a ``Cache-Control`` carrying no-cache/no-store). ``headers`` is any
    case-insensitive mapping (aiohttp's CIMultiDict, urllib's message)."""
    raw = (headers.get(BYPASS_HEADER) or "").strip().lower()
    if raw and raw not in ("0", "false", "no", "off"):
        # Explicit falsy values mean "do not bypass" — a middleware that
        # normalizes boolean headers to "0" must not silently disable the
        # cache for 100% of traffic.
        return True
    cc = (headers.get("Cache-Control") or "").lower()
    return "no-cache" in cc or "no-store" in cc


def normalize_media_type(content_type: str) -> str:
    """Media type without parameters: ``application/json; charset=utf-8`` →
    ``application/json`` (parameters never change the payload semantics the
    cache cares about; charset differences show up in the bytes)."""
    return (content_type or "").split(";", 1)[0].strip().lower()


def canonical_payload(body: bytes, content_type: str = "") -> bytes:
    """Payload bytes with wire-level noise removed.

    JSON media types (``*/json`` and ``*+json``) re-serialize with sorted
    keys and compact separators, so semantically identical documents hash
    identically. Anything that fails to parse — or any binary wire — hashes
    as the raw bytes (never raises)."""
    media = normalize_media_type(content_type)
    if media.endswith("/json") or media.endswith("+json"):
        try:
            return json.dumps(
                json.loads(body.decode("utf-8")),
                sort_keys=True, separators=(",", ":"),
            ).encode("utf-8")
        except (ValueError, UnicodeDecodeError):
            return body
    return body


def request_key(family: str, payload: bytes, content_type: str = "",
                checkpoint: str = "", extra: str = "") -> str:
    """Stable digest over (family, checkpoint, wire format, normalized
    payload[, extra]). ``extra`` carries request addressing that changes the
    answer but lives outside the body — the gateway passes the operation
    tail + query string (``?conf=0.9`` is a different request).

    Fields are length-framed before hashing so no concatenation of values
    can collide with a different split of the same bytes."""
    h = hashlib.sha256()
    for field in (family.encode("utf-8"),
                  checkpoint.encode("utf-8"),
                  normalize_media_type(content_type).encode("utf-8"),
                  extra.encode("utf-8"),
                  canonical_payload(payload, content_type)):
        h.update(len(field).to_bytes(8, "big"))
        h.update(field)
    return f"{family}|{h.hexdigest()}"


def family_of(key: str) -> str:
    """The invalidation namespace a key belongs to."""
    return key.rsplit("|", 1)[0]
