"""Task-store ↔ result-cache coupling.

The async path's cache fill is event-driven, not inline: the gateway stamps a
``CacheKey`` on the task it creates (``gateway/router.py``), the runtime
worker publishes the result into the task store on batch completion exactly as
before, and THIS listener — subscribed to the store's change feed, the same
feed the gateway's long-poll waiters ride — copies the result into the cache
and releases the single-flight registration the moment the task turns
terminal. One fill point covers every transport (queue, push), every producer
(worker, dispatcher serve-from-cache, redrive), and restarts (a replayed
journal re-fires no listeners, so a cold process simply starts with a cold
cache — never a stale one).
"""

from __future__ import annotations

import logging

from ..taskstore import TaskStatus
from ..taskstore.task import endpoint_path
from .keys import family_of

log = logging.getLogger("ai4e_tpu.rescache")


def attach_store(store, cache) -> None:
    """Subscribe ``cache`` to ``store``'s change feed. The store must offer
    ``add_listener`` and ``get_result`` (every Python store does; the native
    store has no listener feed — platform assembly skips the attach there and
    the dispatcher/worker inline paths still serve)."""

    # Pipeline provenance: a composite task's cache key carries stage 1's
    # family, but the RESULT is computed by every downstream stage the task
    # hops to (``AddPipelineTask`` rewrites the endpoint). Record each
    # downstream family — with the cache generation AT the handoff — so the
    # fill can prove no stage's weights swapped mid-flight, and the entry
    # remembers which families can invalidate it later. Keyed by task id;
    # entries are dropped on the same terminal transition that fills/releases,
    # so this holds only in-flight pipeline hops (journal replay fires no
    # listeners — a restart simply starts empty alongside the cold cache).
    hop_gens: dict[str, dict[str, int]] = {}

    def on_task_change(task) -> None:
        key = getattr(task, "cache_key", "")
        if not key:
            return
        status = task.canonical_status
        if status not in TaskStatus.TERMINAL:
            fam = endpoint_path(task.endpoint)
            if fam and fam != family_of(key):
                gens = hop_gens.setdefault(task.task_id, {})
                if fam not in gens:
                    gens[fam] = cache.family_generation(fam)
            return
        gens = hop_gens.pop(task.task_id, None)
        if status == TaskStatus.COMPLETED:
            try:
                found = store.get_result(task.task_id)
            except Exception:  # noqa: BLE001 — cache fill must not break the store
                log.exception("could not read result of %s for cache fill",
                              task.task_id)
                found = None
            if found is not None and cache.fill_inflight(
                    key, task.task_id, found[0], found[1],
                    family_gens=gens):
                # Fill + release happened atomically. The ownership check is
                # the staleness proof: a checkpoint reload invalidates the
                # family AND clears its registrations, so a task that was
                # already executing on the old weights fails it and its
                # result never lands — and ``family_gens`` extends the same
                # proof to downstream pipeline stages reloaded mid-flight.
                # The same check leaves the cache cold (never stale) for
                # journal-restored/requeued tasks that completed without a
                # registration.
                return
        # Terminal without a fill: the key is no longer in flight. A failed
        # leader releases so the NEXT identical request re-executes instead
        # of coalescing onto a corpse forever.
        cache.release_inflight(key, task.task_id)

    store.add_listener(on_task_change)
