"""Per-process runtime vitals — event-loop lag, GC pauses, /proc stats.

Every bench README since r6 blames "swamped variance" on things no
metric measured: the event loop stalling under a blocking call, a GC
pause landing mid-batch, CPU steal on the shared container, RSS creep.
This module is the stdlib-only sampler that makes those visible as
``ai4e_process_*`` series in whatever registry the process already
exports — the control plane's assembly registry, a worker's service
registry, each rig role's per-process registry (which the federation
collector then merges fleet-wide with a ``proc`` label).

Three measurement techniques, none requiring psutil:

- **event-loop lag** (``ai4e_process_loop_lag_seconds``): a timed
  callback measures the delta between when the loop SHOULD have woken
  and when it actually did — any blocking call, GC pause, or CPU
  starvation on the loop thread shows up as lag. This is the number
  that explains "the deadline expired but the worker was idle".
- **GC pauses** (``ai4e_process_gc_pause_seconds``): ``gc.callbacks``
  brackets every collection with start/stop, so pause time is measured
  exactly rather than inferred from lag spikes.
- **/proc reads** (RSS, CPU seconds, open fds, host CPU steal): one
  small read per interval; helpers are exposed for reuse — the soak
  engine's RSS-creep watch and the supervisor's fd forensics use these
  instead of their own parsers.

The sampler keeps a bounded ``recent()`` history ring so the rig's
timeline exporter can plot vitals as Perfetto counter tracks beside the
request timelines (``observability/timeline.py``).
"""

from __future__ import annotations

import asyncio
import gc
import os
import threading
import time
from collections import deque

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry

PROC_ROOT = "/proc"

# Loop-lag histogram buckets: lag below ~1 ms is scheduler noise; the
# interesting range is 10 ms (a heavy callback) through seconds (a
# blocking call on the loop — the bug class AIL001 exists for).
LOOP_LAG_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, float("inf"))
GC_PAUSE_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                    float("inf"))

# The loop-lag max gauge tracks the worst lag over this many recent
# samples — a live dashboard wants "how bad lately", not an
# all-time-high that one startup hiccup pins forever.
_LAG_WINDOW = 30


# -- /proc helpers (shared parsers: soak RSS watch, supervisor fd scan) ------


def read_rss_bytes(pid: int | None = None,
                   proc_root: str = PROC_ROOT) -> float:
    """Resident set size in bytes from ``/proc/<pid>/status`` (VmRSS),
    -1.0 when the process is gone or the file is unreadable."""
    who = "self" if pid is None else str(pid)
    try:
        with open(f"{proc_root}/{who}/status", encoding="ascii") as fh:
            kb = fh.read().split("VmRSS:")[1].split()[0]
        return float(int(kb) * 1024)
    except (OSError, IndexError, ValueError, TypeError):
        return -1.0


def read_rss_mb(pid: int | None = None,
                proc_root: str = PROC_ROOT) -> float:
    """RSS in MiB (one decimal) — the soak engine's historical unit;
    -1.0 = process died (its loop keys on the sign)."""
    rss = read_rss_bytes(pid, proc_root=proc_root)
    return -1.0 if rss < 0 else round(rss / (1024.0 * 1024.0), 1)


def read_cpu_seconds(pid: int | None = None,
                     proc_root: str = PROC_ROOT) -> float:
    """utime+stime of the process in seconds (``/proc/<pid>/stat``
    fields 14/15), -1.0 on failure. The comm field may contain spaces
    and parentheses — parse from the LAST ')' like every correct
    /proc/stat reader."""
    who = "self" if pid is None else str(pid)
    try:
        with open(f"{proc_root}/{who}/stat", encoding="ascii") as fh:
            raw = fh.read()
        fields = raw[raw.rindex(")") + 2:].split()
        # fields[0] is state (field 3); utime/stime are fields 14/15.
        ticks = int(fields[11]) + int(fields[12])
        return ticks / float(os.sysconf("SC_CLK_TCK"))
    except (OSError, IndexError, ValueError, TypeError):
        return -1.0


def read_fd_count(pid: int | None = None,
                  proc_root: str = PROC_ROOT) -> int:
    """Open file descriptors of the process, -1 on failure."""
    who = "self" if pid is None else str(pid)
    try:
        return len(os.listdir(f"{proc_root}/{who}/fd"))
    except OSError:
        return -1


def proc_fd_links(pid: int | str,
                  proc_root: str = PROC_ROOT) -> list[tuple[str, str]]:
    """``(fd, readlink target)`` pairs for one process — the primitive
    the supervisor's socket-inode forensics walks (a target like
    ``socket:[12345]`` identifies a listener). Unreadable fds are
    skipped; an unreadable process yields an empty list."""
    fd_dir = f"{proc_root}/{pid}/fd"
    out: list[tuple[str, str]] = []
    try:
        fds = os.listdir(fd_dir)
    except OSError:
        return out
    for fd in fds:
        try:
            out.append((fd, os.readlink(os.path.join(fd_dir, fd))))
        except OSError:
            continue
    return out


def read_host_cpu_ticks(proc_root: str = PROC_ROOT) -> dict | None:
    """The aggregate ``cpu`` line of ``/proc/stat`` as named tick
    counts (user/nice/system/idle/iowait/irq/softirq/steal), or None
    when unreadable. Steal is the hypervisor running someone else on
    our core — the shared-container variance source the bench READMEs
    keep apologizing for."""
    names = ("user", "nice", "system", "idle", "iowait", "irq",
             "softirq", "steal")
    try:
        with open(f"{proc_root}/stat", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("cpu "):
                    parts = line.split()[1:]
                    return {n: int(parts[i]) if i < len(parts) else 0
                            for i, n in enumerate(names)}
    except (OSError, ValueError):
        return None
    return None


class VitalsSampler:
    """Samples this process's runtime vitals every ``interval_s`` into
    ``ai4e_process_*`` metrics plus a bounded history ring.

    ``start()`` must run on the event loop being measured (the lag
    measurement IS that loop's scheduling delay). ``sample_once`` is
    callable without a loop for tests and for synchronous contexts that
    only want the /proc gauges.
    """

    def __init__(self, metrics: MetricsRegistry | None = None,
                 interval_s: float = 1.0, history: int = 600,
                 proc_root: str = PROC_ROOT):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = interval_s
        self.proc_root = proc_root
        self.metrics = metrics or DEFAULT_REGISTRY
        self._history: deque[dict] = deque(maxlen=history)
        self._hist_lock = threading.Lock()
        self._task: asyncio.Task | None = None
        self._gc_installed = False
        self._gc_t0 = 0.0
        # GC pause seconds accumulated since the last sample tick (the
        # callback fires on whatever thread triggered collection).
        self._gc_accum = 0.0
        self._gc_lock = threading.Lock()
        self._recent_lags: deque[float] = deque(maxlen=_LAG_WINDOW)
        self._last_cpu = -1.0
        self._last_host = read_host_cpu_ticks(proc_root)
        self._m_lag = self.metrics.histogram(
            "ai4e_process_loop_lag_seconds",
            "Event-loop scheduling lag per sampler tick (blocking "
            "calls, GC, CPU starvation on the loop thread)",
            buckets=LOOP_LAG_BUCKETS)
        self._m_lag_max = self.metrics.gauge(
            "ai4e_process_loop_lag_max_seconds",
            f"Worst loop lag over the last {_LAG_WINDOW} samples")
        self._m_gc_pause = self.metrics.histogram(
            "ai4e_process_gc_pause_seconds",
            "Stop-the-world GC pause durations (gc.callbacks)",
            buckets=GC_PAUSE_BUCKETS)
        self._m_gc_total = self.metrics.counter(
            "ai4e_process_gc_collections_total",
            "GC collections by generation")
        self._m_rss = self.metrics.gauge(
            "ai4e_process_rss_bytes", "Resident set size")
        self._m_fds = self.metrics.gauge(
            "ai4e_process_open_fds", "Open file descriptors")
        self._m_cpu = self.metrics.counter(
            "ai4e_process_cpu_seconds_total",
            "Process CPU time consumed (utime+stime)")
        self._m_steal = self.metrics.gauge(
            "ai4e_process_cpu_steal_ratio",
            "Host CPU steal fraction over the last sample interval "
            "(shared-container contention)")

    # -- GC bracketing -------------------------------------------------------

    def _on_gc(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_t0 = time.perf_counter()
            return
        pause = time.perf_counter() - self._gc_t0
        if pause < 0:
            return
        self._m_gc_pause.observe(pause)
        self._m_gc_total.inc(generation=str(info.get("generation", "?")))  # ai4e: noqa[AIL013] — CPython GC generations are 0/1/2 (plus "?"), inherently bounded; not a rollout generation
        with self._gc_lock:
            self._gc_accum += pause

    def install_gc_hook(self) -> None:
        if not self._gc_installed:
            gc.callbacks.append(self._on_gc)
            self._gc_installed = True

    def remove_gc_hook(self) -> None:
        if self._gc_installed:
            try:
                gc.callbacks.remove(self._on_gc)
            except ValueError:
                pass
            self._gc_installed = False

    # -- sampling ------------------------------------------------------------

    def sample_once(self, lag_s: float | None = None) -> dict:
        """One vitals sample: read /proc, update the gauges, append to
        the history ring. ``lag_s`` is supplied by the loop tick (None
        for loop-less callers)."""
        rss = read_rss_bytes(proc_root=self.proc_root)
        fds = read_fd_count(proc_root=self.proc_root)
        cpu = read_cpu_seconds(proc_root=self.proc_root)
        if rss >= 0:
            self._m_rss.set(rss)
        if fds >= 0:
            self._m_fds.set(fds)
        if cpu >= 0:
            if self._last_cpu >= 0 and cpu > self._last_cpu:
                self._m_cpu.inc(cpu - self._last_cpu)
            self._last_cpu = cpu
        steal = None
        host = read_host_cpu_ticks(self.proc_root)
        if host is not None and self._last_host is not None:
            total = sum(host.values()) - sum(self._last_host.values())
            if total > 0:
                steal = (host["steal"] - self._last_host["steal"]) / total
                self._m_steal.set(max(0.0, steal))
        self._last_host = host
        with self._gc_lock:
            gc_pause, self._gc_accum = self._gc_accum, 0.0
        if lag_s is not None:
            self._m_lag.observe(lag_s)
            self._recent_lags.append(lag_s)
            self._m_lag_max.set(max(self._recent_lags))
        sample = {"t": round(time.time(), 3),
                  "rss_bytes": rss, "fds": fds, "cpu_s": round(cpu, 3),
                  "gc_pause_s": round(gc_pause, 6)}
        if lag_s is not None:
            sample["lag_s"] = round(lag_s, 6)
        if steal is not None:
            sample["steal"] = round(max(0.0, steal), 4)
        with self._hist_lock:
            self._history.append(sample)
        return sample

    def recent(self) -> list[dict]:
        """The history ring, oldest first — the timeline exporter's
        counter-track source (``/v1/debug/vitals`` on rig roles)."""
        with self._hist_lock:
            return list(self._history)

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        """Install the GC hook and start the tick loop on the RUNNING
        loop (whose scheduling lag is the thing measured)."""
        if self._task is not None:
            return
        self.install_gc_hook()
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self.remove_gc_hook()
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            due = loop.time() + self.interval_s
            await asyncio.sleep(self.interval_s)
            # The loop woke LATE by exactly its scheduling lag: every
            # blocking call / GC pause / starved-core interval that
            # elapsed while this coroutine was due shows up here.
            lag = max(0.0, loop.time() - due)
            self.sample_once(lag_s=lag)
