"""Spans, propagation, and exporters — one trace per task.

Reference behavior being matched (SURVEY.md §5 "Tracing / profiling"):

- every endpoint execution is wrapped in a span
  (``ai4e_service.py:158-178`` — ``tracer.span(name=trace_name)``);
- trace context crosses process boundaries via the ``x-b3-*`` headers Istio
  propagates and the mixer adapter maps to App Insights
  (``application-insights-istio-adapter/configuration.yaml:10-13``);
- span durations double as latency metrics (the reference's ``Stopwatch``
  blocks around Redis/publish, ``CacheConnectorUpsert.cs:162-201``).

TPU addition: ``device_trace`` bridges spans into the XLA/JAX profiler
(``jax.profiler.TraceAnnotation``) so a TaskId-keyed request span and its
device execution line up in one timeline.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass, field

log = logging.getLogger("ai4e_tpu.trace")

# Same header names Istio/B3 uses (configuration.yaml:10-13) so meshes that
# already speak B3 interoperate with no translation.
TRACE_HEADER = "x-b3-traceid"
SPAN_HEADER = "x-b3-spanid"
PARENT_HEADER = "x-b3-parentspanid"
SAMPLED_HEADER = "x-b3-sampled"


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return os.urandom(8).hex()


@dataclass
class Span:
    name: str
    service: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    task_id: str | None = None
    start: float = 0.0          # epoch seconds
    duration: float = 0.0       # seconds
    status: str = "ok"          # ok | error
    error: str | None = None
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {
            "name": self.name, "service": self.service,
            "trace_id": self.trace_id, "span_id": self.span_id,
            "start": self.start, "duration": self.duration,
            "status": self.status,
        }
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.task_id:
            d["task_id"] = self.task_id
        if self.error:
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = self.attrs
        return d


# -- exporters ---------------------------------------------------------------


class LogExporter:
    """Spans to the Python log — the container-stdout telemetry path."""

    def export(self, span: Span) -> None:
        log.info("span %s/%s trace=%s task=%s %.1fms %s",
                 span.service, span.name, span.trace_id,
                 span.task_id or "-", span.duration * 1e3, span.status)


class JsonlExporter:
    """Append-only JSONL span log (the App Insights sink analogue); one line
    per span, safe across threads."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a", encoding="utf-8")

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), separators=(",", ":"))
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            self._fh.close()


class FanoutExporter:
    """Ship every span to several sinks (e.g. a local JSONL log AND the
    OTLP collector); one sink failing must not starve the others."""

    def __init__(self, exporters):
        self.exporters = list(exporters)

    def export(self, span: Span) -> None:
        for exporter in self.exporters:
            try:
                exporter.export(span)
            except Exception:  # noqa: BLE001 — telemetry must not break serving
                log.exception("span export failed in %s",
                              type(exporter).__name__)

    def close(self) -> None:
        for exporter in self.exporters:
            close = getattr(exporter, "close", None)
            if close is None:
                continue
            try:
                close()
            except Exception:  # noqa: BLE001 — one sink must not starve the rest
                log.exception("exporter close failed in %s",
                              type(exporter).__name__)


class InMemoryExporter:
    """Test sink."""

    def __init__(self):
        self.spans: list[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)

    def by_trace(self, trace_id: str) -> list[Span]:
        return [s for s in self.spans if s.trace_id == trace_id]

    def by_task(self, task_id: str) -> list[Span]:
        return [s for s in self.spans if s.task_id == task_id]


# -- tracer ------------------------------------------------------------------

# (trace_id, span_id, sampled) of the active span in this execution context.
_CURRENT: contextvars.ContextVar[tuple[str, str, bool] | None] = \
    contextvars.ContextVar("ai4e_trace_current", default=None)


class Tracer:
    """Creates spans, propagates context, exports on close.

    Works identically in sync and async code: the active span lives in a
    ``contextvars.ContextVar``, which asyncio tasks inherit and isolate
    automatically (the reference leans on OpenCensus's equivalent machinery
    via ``AzureMonitorLogger``, ``ai4e_service.py:17,53-54``).
    """

    def __init__(self, service: str, exporter=None,
                 sample_rate: float | None = None, metrics=None):
        self.service = service
        # None → follow the process tracer *live* (resolved per span), so
        # configure_tracer() after component construction applies everywhere.
        # Same rule for metrics: a component tracer built WITH a registry
        # (every assembly-owned component passes its own) lands
        # ai4e_span_seconds there; without one it follows the process
        # tracer, then the process default — resolved per observation, not
        # frozen at construction, or the AIL002 leak comes back the moment
        # construction order changes.
        self.exporter = exporter
        self.sample_rate = sample_rate
        self.metrics = metrics
        # (resolved registry, its histogram) — avoids re-taking the
        # registry's get-or-create lock on every span observation while
        # still following a live configure_tracer(metrics=...) rebinding.
        self._span_hist_cache: tuple | None = None

    def _effective_exporter(self):
        if self.exporter is not None:
            return self.exporter
        if self is not _GLOBAL and _GLOBAL.exporter is not None:
            return _GLOBAL.exporter
        return _DEFAULT_EXPORTER

    def _effective_metrics(self):
        # When self IS the global tracer, self.metrics and _GLOBAL.metrics
        # are the same attribute, so one or-chain covers every case.
        from ..metrics import DEFAULT_REGISTRY
        return self.metrics or _GLOBAL.metrics or DEFAULT_REGISTRY

    def _span_seconds(self):
        reg = self._effective_metrics()
        cached = self._span_hist_cache
        if cached is None or cached[0] is not reg:
            cached = (reg, reg.histogram(
                "ai4e_span_seconds", "Span durations by span name"))
            self._span_hist_cache = cached
        return cached[1]

    def _effective_sample_rate(self) -> float:
        if self.sample_rate is not None:
            return self.sample_rate
        if self is not _GLOBAL and _GLOBAL.sample_rate is not None:
            return _GLOBAL.sample_rate
        return 1.0

    # -- propagation -------------------------------------------------------

    def headers(self) -> dict[str, str]:
        """Outbound headers for the active span (inject before any HTTP hop)."""
        cur = _CURRENT.get()
        if cur is None:
            return {}
        trace_id, span_id, sampled = cur
        return {TRACE_HEADER: trace_id, SPAN_HEADER: span_id,
                SAMPLED_HEADER: "1" if sampled else "0"}

    @staticmethod
    def parent_from(headers) -> tuple[str, str, bool] | None:
        """Parse inbound x-b3 headers (case-insensitive mappings like aiohttp's
        work directly)."""
        trace_id = headers.get(TRACE_HEADER)
        if not trace_id:
            return None
        span_id = headers.get(SPAN_HEADER, "")
        sampled = headers.get(SAMPLED_HEADER, "1") != "0"
        return (trace_id, span_id, sampled)

    # -- spans -------------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, task_id: str | None = None,
             headers=None, **attrs):
        """Open a span; yields the ``Span`` (mutable — add attrs mid-flight).

        Parent resolution order: explicit inbound ``headers`` → the active
        span in this context → new root trace. The sampling decision is made
        once at the root and inherited (App Insights samples the same way,
        ``CacheManager/host.json:5-8``).
        """
        parent = self.parent_from(headers) if headers else None
        if parent is None:
            parent = _CURRENT.get()
        if parent is not None:
            trace_id, parent_id, sampled = parent
            parent_id = parent_id or None
        else:
            trace_id, parent_id = _new_trace_id(), None
            sampled = _sample(trace_id, self._effective_sample_rate())
        if self._effective_sample_rate() <= 0.0:
            # Hard off (trace_enabled=0) beats inherited x-b3-sampled:1 —
            # a B3-speaking mesh stamps every request as sampled, and the
            # kill switch must still kill local export.
            sampled = False

        span = Span(name=name, service=self.service, trace_id=trace_id,
                    span_id=_new_span_id(), parent_id=parent_id,
                    task_id=task_id, start=time.time(), attrs=dict(attrs))
        token = _CURRENT.set((trace_id, span.span_id, sampled))
        t0 = time.perf_counter()
        try:
            yield span
        except BaseException as exc:
            span.status = "error"
            span.error = f"{type(exc).__name__}: {exc}"
            raise
        finally:
            _CURRENT.reset(token)
            span.duration = time.perf_counter() - t0
            self._span_seconds().observe(span.duration, name=name,
                                         service=self.service)
            if sampled:
                try:
                    self._effective_exporter().export(span)
                except Exception:  # noqa: BLE001 — telemetry must not break serving
                    log.exception("span export failed")

    def current_trace_id(self) -> str | None:
        cur = _CURRENT.get()
        return cur[0] if cur else None


def _sample(trace_id: str, rate: float) -> bool:
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    # Deterministic per-trace: every service in the hop chain keeps or drops
    # the same traces.
    return (int(trace_id[:8], 16) / 0xFFFFFFFF) < rate


# -- process-global tracer ---------------------------------------------------

_DEFAULT_EXPORTER = LogExporter()
_GLOBAL = Tracer("ai4e")
_UNSET = object()


def get_tracer() -> Tracer:
    return _GLOBAL


def configure_tracer(service: str | None = None, exporter=_UNSET,
                     sample_rate=_UNSET, metrics=_UNSET) -> Tracer:
    """Reconfigure the process tracer in place. Component tracers built
    without an explicit exporter/sample_rate/metrics (every
    service/gateway/dispatcher default) follow these settings live. Pass
    ``None`` explicitly to reset a field to its default (LogExporter /
    rate 1.0 / the process-default metrics registry)."""
    if service is not None:
        _GLOBAL.service = service
    if exporter is not _UNSET:
        _GLOBAL.exporter = exporter
    if sample_rate is not _UNSET:
        _GLOBAL.sample_rate = sample_rate
    if metrics is not _UNSET:
        _GLOBAL.metrics = metrics
    return _GLOBAL


# -- XLA profiler bridge -----------------------------------------------------


@contextlib.contextmanager
def device_trace(name: str):
    """Annotate device work so it lines up with request spans in the JAX
    profiler timeline (``jax.profiler.TraceAnnotation``); no-op when the
    profiler isn't active. Use around ``runtime.run_batch`` calls."""
    try:
        import jax.profiler
        with jax.profiler.TraceAnnotation(name):
            yield
    except ImportError:
        yield
