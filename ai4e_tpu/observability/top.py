"""``python -m ai4e_tpu top`` — a live terminal dashboard over the
fleet snapshot (docs/observability.md).

Three sources, same frame:

- ``--collector URL``  — poll a running collector's ``/v1/debug/fleet``
  (the rig's collector role, or anything serving that JSON);
- ``--spec topology.json`` — scrape the topology's roles directly with
  an in-process ``FleetCollector`` (no collector role needed);
- ``--targets name=url,name=url`` — ad-hoc target list (e.g. one
  control plane + its workers outside the rig).

Per-proc columns: up, requests/s (delta between frames), task goodput %
(ok / terminal outcomes), max SLO burn, event-loop lag, RSS, fds — the
per-role req/s, goodput, SLO burn, loop lag, RSS view the tentpole
names. The renderer is a pure function of two snapshots so tests (and
``--once``) need no terminal."""

from __future__ import annotations

import asyncio
import sys
import time


def _fmt_bytes(n) -> str:
    if n is None or n <= 0:
        return "-"
    return f"{n / (1024.0 * 1024.0):.0f}M"


def _fmt_lag(s) -> str:
    if s is None:
        return "-"
    return f"{s * 1e3:.0f}ms" if s < 10 else f"{s:.0f}s"


def _rate(cur: dict, prev: dict | None, name: str) -> str:
    if prev is None:
        return "-"
    dt = cur.get("t", 0.0) - prev.get("t", 0.0)
    if dt <= 0:
        return "-"
    a = cur["per_proc"].get(name, {}).get("requests_total") or 0.0
    b = prev["per_proc"].get(name, {}).get("requests_total") or 0.0
    return f"{max(0.0, a - b) / dt:.1f}"


def render_top(snapshot: dict, prev: dict | None = None) -> str:
    """One dashboard frame from a fleet snapshot (+ the previous one
    for rates)."""
    fleet = snapshot.get("fleet", {})
    cons = snapshot.get("conservation", {})
    if not cons.get("checked", True):
        status = "unchecked"  # non-rig surface: inputs are not sound
    else:
        status = "OK" if cons.get("ok", True) else "VIOLATED"
        if cons.get("degraded"):
            status += " (degraded: counters lost with killed/restarted procs)"
    lines = [
        f"fleet  t={snapshot.get('t', 0.0):.0f}  "
        f"up {fleet.get('up', 0)}/{snapshot.get('targets', 0)}  "
        f"admitted {fleet.get('admitted', 0.0):.0f}  "
        f"terminal {fleet.get('terminal', 0.0):.0f}  "
        f"in-flight {fleet.get('in_flight', 0.0):.0f}  "
        f"conservation {status}",
        f"{'proc':<16} {'role':<11} {'up':<3} {'req/s':>7} "
        f"{'goodput':>8} {'burn':>6} {'lag':>7} {'rss':>7} {'fds':>5}",
    ]
    for name in sorted(snapshot.get("per_proc", ())):
        p = snapshot["per_proc"][name]
        outcomes = p.get("outcomes") or {}
        terminal = sum(v for k, v in outcomes.items() if k != "shed")
        good = outcomes.get("ok", 0.0)
        goodput = f"{100.0 * good / terminal:.1f}%" if terminal else "-"
        burn = p.get("slo_burn_max")
        fds = p.get("open_fds")
        lines.append(
            f"{name:<16} {p.get('role', '?'):<11} "
            f"{'up' if p.get('up') else 'DN':<3} "
            f"{_rate(snapshot, prev, name):>7} {goodput:>8} "
            f"{f'{burn:.1f}' if burn is not None else '-':>6} "
            f"{_fmt_lag(p.get('loop_lag_max_s')):>7} "
            f"{_fmt_bytes(p.get('rss_bytes')):>7} "
            f"{f'{fds:.0f}' if fds else '-':>5}")
    violations = cons.get("confirmed_violations") or []
    if violations:
        lines.append(f"!! {len(violations)} confirmed conservation "
                     f"violation(s); latest: {violations[-1]}")
    return "\n".join(lines)


async def run_top(collector: str | None = None,
                  spec: str | None = None,
                  targets: str | None = None,
                  interval: float = 2.0, once: bool = False,
                  out=None) -> int:
    """The CLI body; returns an exit code. Exactly one source must be
    given."""
    from .federation import fetch_json

    out = out or (lambda s: print(s, flush=True))
    own = None
    if collector:
        url = collector.rstrip("/") + "/v1/debug/fleet"

        async def fetch() -> dict:
            snap = await asyncio.to_thread(fetch_json, url, 5.0)
            if snap is None:
                raise OSError(f"no fleet snapshot at {url}")
            return snap
    elif spec or targets:
        from .federation import FleetCollector
        if spec:
            from ..rig.topology import Topology
            topo = Topology.load(spec)
            target_map = {n: u for n, u in topo.metrics_urls().items()
                          if n != "collector"}
            own = FleetCollector(target_map, interval_s=interval)
        else:
            try:
                target_map = dict(pair.split("=", 1)
                                  for pair in targets.split(",") if pair)
            except ValueError:
                print("top: --targets wants name=url,name=url "
                      f"(got {targets!r})", file=sys.stderr)
                return 2
            # Ad-hoc targets: the surface is unknown (sync traffic /
            # admission refusals feed outcomes with no admissions), so
            # the conservation check's inputs are not sound — view
            # only (federation.py docstring).
            own = FleetCollector(target_map, interval_s=interval,
                                 conservation=False)

        async def fetch() -> dict:
            await own.scrape_once()
            return own.snapshot()
    else:
        print("top: pass --collector URL, --spec topology.json, or "
              "--targets name=url,...", file=sys.stderr)
        return 2

    prev = None
    try:
        while True:
            t0 = time.monotonic()
            try:
                snap = await fetch()
            except OSError as exc:
                out(f"top: fleet source unreachable: {exc}")
                if once:
                    return 1
                await asyncio.sleep(interval)
                continue
            frame = render_top(snap, prev)
            if once:
                out(frame)
                return 0
            # Clear + home, then the frame: a live dashboard, not a log.
            out("\x1b[2J\x1b[H" + frame)
            prev = snap
            await asyncio.sleep(max(0.2, interval -
                                    (time.monotonic() - t0)))
    except (KeyboardInterrupt, asyncio.CancelledError):
        return 0
