"""Terminal trace viewer over the JSONL span log — the end-to-end
transaction view App Insights gave the reference (its operators searched a
TaskId and got the request's span tree across services; here
``python -m ai4e_tpu trace --task-id …`` renders the same tree from the
``AI4E_OBSERVABILITY_TRACE_EXPORT_PATH`` log, no SaaS required; the OTLP
exporter still feeds Cloud Trace for the hosted view).

Spans are the ``tracing.Span.to_dict`` records: one JSON object per line,
``trace_id``/``span_id``/``parent_id`` linkage, ``task_id`` correlation,
epoch ``start`` + ``duration`` seconds. The viewer is tolerant of the log
being live: truncated/garbage lines are skipped, orphan spans (parent not
exported yet, or sampled out) render as roots.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


def load_spans(path: str) -> list[dict]:
    """Read a JSONL span log, skipping non-JSON / non-object lines (the
    file may be mid-write by a live service)."""
    spans = []
    with open(path, encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (isinstance(rec, dict) and rec.get("trace_id")
                    and rec.get("span_id")):
                spans.append(rec)
    return spans


def select_traces(spans: list[dict], task_id: str | None = None,
                  trace_id: str | None = None) -> list[dict]:
    """Spans of the selected trace(s). ``task_id`` selects every trace any
    matching span belongs to (a pipeline task traverses several services
    under one trace; a redriven task may own several traces) and returns
    ALL spans of those traces — including infrastructure spans that don't
    carry the task_id themselves."""
    if trace_id:
        ids = {trace_id}
    elif task_id:
        ids = {s["trace_id"] for s in spans if s.get("task_id") == task_id}
    else:
        ids = {s["trace_id"] for s in spans}
    return [s for s in spans if s["trace_id"] in ids]


@dataclass
class _Node:
    span: dict
    children: list = field(default_factory=list)


def _ms(seconds: float) -> str:
    return f"{seconds * 1000.0:.1f}ms"


def _trees(spans: list[dict]) -> list[_Node]:
    """Parent-linked forest, roots and siblings in start order. A span
    whose parent is absent (not exported, sampled out) roots its subtree."""
    nodes = {s["span_id"]: _Node(s) for s in spans}
    roots = []
    for node in nodes.values():
        parent = nodes.get(node.span.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node.children.sort(key=lambda n: n.span.get("start", 0.0))
    roots.sort(key=lambda n: n.span.get("start", 0.0))
    return roots


def _render_node(node: _Node, t0: float, prefix: str, last: bool,
                 out: list[str]) -> None:
    s = node.span
    connector = "└─ " if last else "├─ "
    line = (f"{prefix}{connector}{s.get('name', '?')} "
            f"[{s.get('service', '?')}]  "
            f"+{_ms(s.get('start', t0) - t0)} {_ms(s.get('duration', 0.0))}")
    if s.get("status") == "error":
        line += f"  ERROR: {s.get('error', '')}"
    attrs = s.get("attrs") or {}
    if attrs:
        line += "  " + " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    out.append(line)
    child_prefix = prefix + ("   " if last else "│  ")
    for i, child in enumerate(node.children):
        _render_node(child, t0, child_prefix, i == len(node.children) - 1,
                     out)


def render_trace(spans: list[dict]) -> str:
    """One trace per block: header (trace id, span count, wall span, task),
    then the indented tree with per-span offset/duration/status."""
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    blocks = []
    for tid, trace_spans in sorted(
            by_trace.items(),
            key=lambda kv: min(s.get("start", 0.0) for s in kv[1])):
        t0 = min(s.get("start", 0.0) for s in trace_spans)
        t1 = max(s.get("start", 0.0) + s.get("duration", 0.0)
                 for s in trace_spans)
        tasks = sorted({s["task_id"] for s in trace_spans
                        if s.get("task_id")})
        errors = sum(1 for s in trace_spans if s.get("status") == "error")
        header = (f"trace {tid}  {len(trace_spans)} spans  {_ms(t1 - t0)}"
                  + (f"  task {', '.join(tasks)}" if tasks else "")
                  + (f"  {errors} ERROR" if errors else ""))
        out = [header]
        roots = _trees(trace_spans)
        for i, root in enumerate(roots):
            _render_node(root, t0, "", i == len(roots) - 1, out)
        blocks.append("\n".join(out))
    return "\n\n".join(blocks)


def render_list(spans: list[dict], limit: int = 20) -> str:
    """Most-recent-first trace summary — the transaction-search results
    list: trace id, root span name, span count, wall time, task, errors."""
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    rows = []
    for tid, trace_spans in by_trace.items():
        t0 = min(s.get("start", 0.0) for s in trace_spans)
        t1 = max(s.get("start", 0.0) + s.get("duration", 0.0)
                 for s in trace_spans)
        # Root = the parentless span (clock skew across services can give
        # a CHILD the earliest wall-clock start); _trees applies the same
        # rule and falls back to start order for orphans.
        root = _trees(trace_spans)[0].span
        tasks = sorted({s["task_id"] for s in trace_spans
                        if s.get("task_id")})
        errors = sum(1 for s in trace_spans if s.get("status") == "error")
        rows.append((t0, f"{tid}  {root.get('name', '?')} "
                         f"[{root.get('service', '?')}]  "
                         f"{len(trace_spans)} spans  {_ms(t1 - t0)}"
                         + (f"  task {tasks[0]}" if tasks else "")
                         + (f"  {errors} ERROR" if errors else "")))
    rows.sort(key=lambda r: r[0], reverse=True)
    return "\n".join(r[1] for r in rows[:limit])
